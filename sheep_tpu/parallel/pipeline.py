"""Sharded multi-device partition pipeline (SURVEY.md §2 #9, §3.1).

The comm surface mirrors the reference's three MPI crossings exactly
(SURVEY.md §3.1), as XLA collectives on the ``shards`` mesh axis:

  1. shard scatter     -> host round-robins edge chunks to devices
                          (EdgeStream chunk index % D), device_put with a
                          NamedSharding — no collective, just placement
  2. tree-merge reduce -> butterfly allreduce with *forest merge* as the
                          combiner: log2(D) host-driven ppermute rounds,
                          each device ships compacted boundary pairs (or
                          the dense O(V) table when occupancy is high)
                          over ICI and folds the received constraints
                          with the adaptive elimination fixpoint; after
                          the last round every device holds the global
                          tree (T is associative + commutative, so the
                          butterfly is valid)
  3. score all-reduce  -> psum of (cut, total) counters

Degrees use per-device partial counts summed once at the end (one
all-reduce of an O(V) vector), so the streaming passes are collective-free:
all cross-device traffic is O(V log D + V), independent of E.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheep_tpu import obs
from sheep_tpu.analysis import sanitize
from sheep_tpu.io.devicestream import is_device_stream, note_device_chunks
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.ops import score as score_ops
from sheep_tpu.parallel.mesh import SHARD_AXIS, shard_map


def chunk_batches(stream, chunk_edges: int, n_devices: int, n: int,
                  shard: int = 0, num_shards: int = 1, start_chunk: int = 0,
                  byte_range: bool = False):
    """Group the chunk stream into (D, C, 2) int32 host batches, one chunk
    per device, padded with the sentinel vertex n. Yields (batch, count)."""
    from sheep_tpu.backends.tpu_backend import pad_chunk

    batch = np.full((n_devices, chunk_edges, 2), n, dtype=np.int32)
    filled = 0
    for chunk in stream.chunks(chunk_edges, shard=shard, num_shards=num_shards,
                               start_chunk=start_chunk, byte_range=byte_range):
        batch[filled] = pad_chunk(chunk, chunk_edges, n)
        filled += 1
        if filled == n_devices:
            yield batch, filled
            batch = np.full((n_devices, chunk_edges, 2), n, dtype=np.int32)
            filled = 0
    if filled:
        yield batch, filled


def use_byte_range(stream, procs: int) -> bool:
    """PLAIN text files in multi-process runs shard by byte span so each
    process parses only ~file/P (VERDICT r1 item 7); binary/CSR formats
    already seek in O(1) per chunk, and gzip members are one sequential
    stream (no seeks — EdgeStream serves them round-robin by chunk
    index, the semantics the non-byte_range batch math assumes)."""
    return (procs > 1 and stream.path is not None
            and stream.fmt == "text")


def iter_batches_lockstep(stream, cs: int, rows: int, n: int, proc: int,
                          procs: int, start_chunk: int = 0,
                          byte_range: bool = False):
    """Yield (rows, C, 2) host batches from this process's shard of the
    chunk stream. Multi-host: every process yields the SAME number of
    batches (stragglers pad with all-sentinel batches) so per-batch
    collectives stay in lockstep — the count comes from the stream length
    (binary: O(1); text: each process counts its OWN byte span, then one
    tiny allgather agrees on the max)."""
    gen = (b for b, _ in chunk_batches(
        stream, cs, rows, n, shard=proc, num_shards=procs,
        start_chunk=start_chunk, byte_range=byte_range))
    if procs == 1:
        yield from gen
        return
    if byte_range:
        # per-process local chunk counts differ (spans are byte-, not
        # edge-balanced); allgather them once to agree on the batch
        # count. Local chunk j of process p = global chunk j*P + p, so
        # the start_chunk skip math matches the round-robin case.
        from jax.experimental import multihost_utils

        mine = -(-stream.count_edges_in_span(proc, procs) // cs)
        counts = np.asarray(multihost_utils.process_allgather(
            np.array([mine], dtype=np.int64))).reshape(-1)

        def owned(p):
            done = max(0, (start_chunk - p + procs - 1) // procs)
            return max(0, int(counts[p]) - done)
    else:
        total = -(-stream.num_edges // cs)  # total chunks in stream

        def owned(p):  # chunks i in [start_chunk, total) with i % procs == p
            full = max(0, (total - p + procs - 1) // procs)
            done = max(0, (start_chunk - p + procs - 1) // procs)
            return full - done

    nb = max(-(-owned(p) // rows) for p in range(procs))
    produced = 0
    for b in gen:
        yield b
        produced += 1
    empty = np.full((rows, cs, 2), n, np.int32)
    for _ in range(nb - produced):
        yield empty


def device_lockstep_batches(stream, cs: int, rows: int, n: int, sharding,
                            start_chunk: int = 0, stats=None):
    """(rows, C, 2) int32 GLOBAL device batches synthesized ON DEVICE
    from a :func:`~sheep_tpu.io.devicestream.is_device_stream` input —
    the single-process device twin of :func:`iter_batches_lockstep`:
    batch b row j carries global chunk ``start_chunk + b*rows + j``,
    chunk indices past the stream end synthesize the inert all-sentinel
    chunk, so the batch sequence is bit-identical to the host path's
    padded batches while paying ZERO host bytes per chunk (ISSUE 12;
    the sharded/bigv soak ingest this replaces generated on host and
    re-crossed the link every pass).

    Each row is synthesized via the stream's jitted device kernel and
    placed on its owning device (``device_chunk_on`` semantics — a
    device-to-device move on a real mesh, never a host crossing), then
    the global array assembles with
    ``jax.make_array_from_single_device_arrays``. Multi-host callers
    keep the host lockstep path: per-process assembly goes through
    ``make_array_from_process_local_data``, which takes host rows."""
    shape = (rows, cs, 2)
    # device -> owned row index, from the sharding itself (robust to
    # device enumeration order)
    owners = sorted(
        ((idx[0].start or 0, dev)
         for dev, idx in sharding.addressable_devices_indices_map(
             shape).items()),
        key=lambda t: t[0])
    total = stream.num_device_chunks(cs)
    n_batches = max(0, -(-(total - start_chunk) // rows))

    def place(dev, idx):
        # device_chunk_on = the protocol's placement hook (default:
        # synthesize on the default device, move device-to-device —
        # zero host bytes; a stream may override it to synthesize on
        # the target directly). Duck-typed streams without the hook
        # get the default move.
        if hasattr(stream, "device_chunk_on"):
            return stream.device_chunk_on(dev, idx, cs, n)
        return jax.device_put(stream.device_chunk(idx, cs, n), dev)

    for b in range(n_batches):
        shards = []
        for j, dev in owners:
            chunk = place(dev, start_chunk + b * rows + j)
            shards.append(chunk[None])
        # count only the REAL chunks of a partial final batch (pad rows
        # are inert sentinels, and the tpu driver's count is exact —
        # the two drivers must report the same ingest telemetry for
        # the same input)
        note_device_chunks(stats,
                           min(rows, total - (start_chunk + b * rows)))
        yield jax.make_array_from_single_device_arrays(
            shape, sharding, shards)


class _PassThrough:
    """The prefetch surface (with/iter/close) over a plain generator,
    for DEVICE-SYNTH batch streams: a worker thread buffering global
    device arrays would hold queue-depth x batch HBM the membudget
    model never counts, and there is no host I/O to overlap anyway
    (synthesis is already-async device work). Host-format streams keep
    the real :func:`~sheep_tpu.utils.prefetch.prefetch`."""

    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        return iter(self._gen)

    def close(self) -> None:
        close = getattr(self._gen, "close", None)
        if close is not None:
            close()

    def __enter__(self) -> "_PassThrough":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _grouped(iterable, batch: int):
    """Plain (worker-less) grouping into lists of up to ``batch`` items
    — the device-synth twin of prefetch_batched's inner generator."""
    buf: list = []
    for item in iterable:
        buf.append(item)
        if len(buf) == batch:
            yield buf
            buf = []
    if buf:
        yield buf


class ShardedPipeline:
    """Compiled sharded pipeline for a fixed (n, chunk_edges, mesh)."""

    def __init__(self, n: int, chunk_edges: int, mesh, lift_levels: int = 0,
                 segment_rounds: int = 32, warm_schedule=((1, 8),),
                 dispatch_batch: int = 1, inflight: int = 1,
                 donate: bool = False):
        self.n = n
        self.cs = chunk_edges
        self.mesh = mesh
        self.lift_levels = lift_levels
        # batched segment dispatch (ops/elim.py batch_segment_fixpoint):
        # stage N sharded batches as (D, N, C) oriented blocks and fold
        # them per device inside single bounded programs, pulling one
        # replicated packed-stats word per execution instead of one
        # changed/live pair per segment step. 1 = per-segment (the
        # adaptive _fold_actives loop); the merged forest is the same
        # unique fixpoint either way.
        self.dispatch_batch = max(1, int(dispatch_batch))
        # asynchronous dispatch pipeline depth for the batched path
        # (ISSUE 4): keep up to D issued fold_batch_step executions in
        # flight, speculatively re-dispatching the staged blocks before
        # the replicated stats word is pulled, and read the words
        # one-behind — every process runs the same deterministic driver
        # on the same replicated stats, so the collective schedules
        # stay in lockstep (speculative executions are collectives too,
        # issued identically everywhere). Unneeded speculations are
        # discarded unread; their output is the bit-identical
        # re-confirmation of the drained blocks.
        if inflight < 1:
            raise ValueError("inflight must be >= 1 here (backends "
                             "resolve 0 = auto before constructing)")
        self.inflight = int(inflight)
        # donate the per-device tables + staging blocks into each
        # batched execution (ops/elim.py donation rationale); pure
        # buffer aliasing, identical results
        self.donate = bool(donate)
        # fixpoint rounds per device execution in the build phase; the
        # host loops bounded segments so no single accelerator call runs
        # unboundedly long (the TPU worker watchdog kills those)
        self.segment_rounds = segment_rounds
        # low-lift warm rounds before full-depth rounds, as in the
        # single-device adaptive fold: a full-buffer round costs
        # ~lift_levels x width gathers per device and most slots retire
        # early without long jumps (tools/tune_fixpoint.py sweeps)
        self.warm_schedule = tuple(warm_schedule)
        d = mesh.devices.size
        self.n_devices = d
        self.rounds = max(1, math.ceil(math.log2(d))) if d > 1 else 0
        # multi-host layout: this process owns n_local contiguous mesh rows
        # (jax.devices() orders by process); chunks round-robin over
        # *processes* at the stream level and over local rows within one
        self.procs = len({dev.process_index for dev in mesh.devices.flat})
        self.proc = jax.process_index() if self.procs > 1 else 0
        self.n_local = (sum(1 for dev in mesh.devices.flat
                            if dev.process_index == jax.process_index())
                        if self.procs > 1 else d)
        if self.procs > 1 and self.n_local * self.procs != d:
            raise ValueError("uneven devices per process not supported")

        self.batch_sharding = NamedSharding(mesh, P(SHARD_AXIS, None, None))
        self.state_sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
        self.repl_sharding = NamedSharding(mesh, P())

        n_ = self.n
        lift = self.lift_levels

        @partial(jax.jit,
                 in_shardings=(self.state_sharding, self.batch_sharding),
                 out_shardings=self.state_sharding)
        def deg_step(deg_all, batch):
            def f(deg_local, chunk_local):
                return degrees_ops.degree_chunk(
                    deg_local[0], chunk_local[0], n_)[None]
            return shard_map(f, mesh=mesh,
                             in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None, None)),
                             out_specs=P(SHARD_AXIS, None))(deg_all, batch)

        @partial(jax.jit, out_shardings=self.repl_sharding)
        def deg_reduce(deg_all):
            return jnp.sum(deg_all, axis=0, dtype=jnp.int32)

        @partial(jax.jit, out_shardings=(self.repl_sharding, self.repl_sharding))
        def make_order(deg_total):
            return order_ops.elimination_order(deg_total, n_)

        seg_ = self.segment_rounds

        @partial(jax.jit,
                 in_shardings=(self.batch_sharding, self.repl_sharding),
                 out_shardings=(self.state_sharding, self.state_sharding))
        def orient_step(batch, pos):
            def f(chunk_local, pos_):
                lo, hi = elim_ops.orient_edges_pos(chunk_local[0], pos_, n_)
                return lo[None], hi[None]
            return shard_map(
                f, mesh=mesh,
                in_specs=(P(SHARD_AXIS, None, None), P()),
                out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)))(
                    batch, pos)

        def _make_fold_seg(small: bool, warm_levels: int = 0,
                           warm_rounds: int = 0):
            """Segment step over whatever active-buffer width the inputs
            have (one compiled program per width). Everything is POSITION
            SPACE (tables P[p] = parent position, actives = position
            pairs), so the compiled programs carry no pos/order tables
            and no per-segment conversion gathers — the orient step maps
            in, and the caller converts the merged table out once.
            ``small`` selects jump-mode rounds (no O(V) lifting-table
            rebuild) for the compacted tail. Returns carried state +
            pmax'd any-device-changed flag and max live count,
            replicated, so every device AND process makes the same host
            decision."""
            @partial(jax.jit,
                     in_shardings=(self.state_sharding, self.state_sharding,
                                   self.state_sharding),
                     out_shardings=(self.state_sharding, self.state_sharding,
                                    self.state_sharding, self.repl_sharding,
                                    self.repl_sharding, self.repl_sharding))
            def fold_seg_step(P_all, lo_all, hi_all):
                def f(P_local, lo_local, hi_local):
                    if small:
                        lo2, hi2, Pn, sv = \
                            elim_ops.fold_segment_small_pos(
                                P_local[0], lo_local[0], hi_local[0], n_,
                                segment_rounds=max(seg_, 64))
                    elif warm_levels:
                        lo2, hi2, Pn, sv = \
                            elim_ops.fold_segment_pos(
                                P_local[0], lo_local[0], hi_local[0], n_,
                                lift_levels=warm_levels,
                                segment_rounds=warm_rounds,
                                descent="stream")
                    else:
                        lo2, hi2, Pn, sv = \
                            elim_ops.fold_segment_pos(
                                P_local[0], lo_local[0], hi_local[0], n_,
                                lift_levels=lift, segment_rounds=seg_)
                    # sv = (changed, rounds, live) computed in-program;
                    # rounds ride out pmax'd (lockstep wall = slowest
                    # device) for the O(Δ) update instrumentation
                    any_changed = lax.pmax(sv[0], SHARD_AXIS)
                    max_live = lax.pmax(sv[2], SHARD_AXIS)
                    rounds_mx = lax.pmax(sv[1], SHARD_AXIS)
                    return (Pn[None], lo2[None], hi2[None], any_changed,
                            max_live, rounds_mx)
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None),
                              P(SHARD_AXIS, None)),
                    out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None),
                               P(SHARD_AXIS, None), P(), P(), P()))(
                        P_all, lo_all, hi_all)
            return fold_seg_step

        # pmax'd live count of a (D, W) active buffer — one tiny
        # replicated scalar, no fold. Lets the merge right-size a
        # received buffer BEFORE paying a full-width fold segment (merge
        # buffers are usually nearly empty: O(boundary) pairs in an
        # O(V)-capacity exchange). One instance serves every width: jit
        # caches an executable per input shape.
        @partial(jax.jit, in_shardings=(self.state_sharding,),
                 out_shardings=self.repl_sharding)
        def live_count(lo_all):
            def f(lo_local):
                live = jnp.sum(lo_local[0] != n_, dtype=jnp.int32)
                return lax.pmax(live, SHARD_AXIS)
            return shard_map(
                f, mesh=mesh, in_specs=(P(SHARD_AXIS, None),),
                out_specs=P())(lo_all)

        def _make_compact(to_size: int):
            @partial(jax.jit,
                     in_shardings=(self.state_sharding, self.state_sharding),
                     out_shardings=(self.state_sharding, self.state_sharding))
            def compact_step(lo_all, hi_all):
                def f(lo_local, hi_local):
                    lo2, hi2 = elim_ops.compact_actives(
                        lo_local[0], hi_local[0], n_, to_size)
                    return lo2[None], hi2[None]
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
                    out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)))(
                        lo_all, hi_all)
            return compact_step

        self.orient_step = orient_step
        self._fold_full = _make_fold_seg(False)
        self._fold_small = _make_fold_seg(True)
        self._live_count = live_count
        self._fold_warm = [
            _make_fold_seg(False, warm_levels=wl, warm_rounds=wr)
            for wr, wl in self.warm_schedule]
        self._make_compact = _make_compact
        self._compact_cache: dict = {}

        d_ = self.n_devices
        r_ = self.rounds

        def _make_exchange(cap0: int, r: int):
            """One butterfly exchange round, as its own jitted step: each
            device ships its forest to its XOR partner and receives the
            partner's as an ACTIVE CONSTRAINT buffer for the host-driven
            adaptive fold. In position space a table entry p -> P[p] IS
            the constraint (loP=p, hiP=P[p]) — no order lookup anywhere.

            ``cap0`` = per-round payload capacity (entries); 0 means dense
            (ship the whole O(V) table). Compact rounds ship
            (position, parent-position) pairs of the non-sentinel entries
            only — SURVEY.md §7 hard part #4's O(boundary) traffic.
            Capacity doubles per round: a merged forest has at most
            count_A + count_B parent entries, so cap0 >= the initial max
            occupancy makes cap0 * 2^r sufficient for round r — checked
            on host before selecting this path. Once 2 * cap is no
            smaller than the table itself, the round ships dense."""
            perm = [(i, i ^ (1 << r)) for i in range(d_)
                    if (i ^ (1 << r)) < d_]
            cap = min(cap0 << r, n_ + 1) if cap0 else n_ + 1
            compact = 2 * cap < n_ + 1

            @partial(jax.jit,
                     in_shardings=(self.state_sharding,),
                     out_shardings=(self.state_sharding, self.state_sharding))
            def exchange(P_all):
                def f(P_local):
                    table = P_local[0]
                    idx = lax.axis_index(SHARD_AXIS)
                    valid = (idx ^ (1 << r)) < d_
                    if compact:
                        sel = jnp.nonzero(table[:n_] != n_, size=cap,
                                          fill_value=n_)[0].astype(jnp.int32)
                        # fill slots index the sentinel: table[n] == n
                        payload = jnp.stack([sel, table[sel]])
                        recv = lax.ppermute(payload, SHARD_AXIS, perm)
                        # out-of-range XOR partners receive zeros;
                        # neutralize to the inert (n, n) pair
                        recv = jnp.where(valid, recv, jnp.int32(n_))
                        lo, hi = recv[0], recv[1]
                        bad = (lo >= n_) | (hi >= n_)
                        lo = jnp.where(bad, n_, lo)
                        hi = jnp.where(bad, n_, hi)
                    else:
                        other = lax.ppermute(table, SHARD_AXIS, perm)
                        other = jnp.where(valid, other, jnp.int32(n_))
                        p = jnp.arange(n_ + 1, dtype=jnp.int32)
                        has = other < n_
                        lo = jnp.where(has, p, n_)
                        hi = jnp.where(has, other, n_)
                    return lo[None], hi[None].astype(jnp.int32)
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None),),
                    out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)))(
                        P_all)
            return exchange

        @partial(jax.jit, out_shardings=self.repl_sharding)
        def extract_merged(P_all):
            return P_all[0]

        @partial(jax.jit, out_shardings=self.repl_sharding)
        def to_minp(P_repl, pos):
            """Replicated position-space table -> vertex-space minp (the
            stable checkpoint/result encoding)."""
            return P_repl[pos]

        self._make_exchange = _make_exchange
        self._exchange_cache: dict = {}
        self._extract_merged = extract_merged
        self.to_minp = to_minp

        @partial(jax.jit, out_shardings=self.repl_sharding)
        def max_occupancy(forest_all):
            """Largest per-device count of non-sentinel forest entries —
            one tiny all-reduce, used to pick the compact-merge capacity."""
            return jnp.max(jnp.sum((forest_all[:, :n_] != n_)
                                   .astype(jnp.int32), axis=1))

        self.max_occupancy = max_occupancy

        @partial(jax.jit,
                 in_shardings=(self.batch_sharding, self.repl_sharding),
                 out_shardings=self.repl_sharding)
        def score_step(batch, assign):
            """Per-batch (cut, total) summed over devices (comm point 3)."""
            def f(chunk_local, assign_):
                c, t = score_ops.score_chunk(chunk_local[0], assign_, n_)
                return lax.psum(jnp.stack([c, t])[None], SHARD_AXIS)
            return shard_map(
                f, mesh=mesh,
                in_specs=(P(SHARD_AXIS, None, None), P()),
                out_specs=P(SHARD_AXIS, None))(batch, assign)[0]

        self.deg_step = deg_step
        self.deg_reduce = deg_reduce
        self.make_order = make_order
        self.score_step = score_step

        nb = self.dispatch_batch
        if nb > 1 or self.inflight > 1:
            self.block_sharding = NamedSharding(
                mesh, P(SHARD_AXIS, None, None))
            self.block_edges_sharding = NamedSharding(
                mesh, P(SHARD_AXIS, None, None, None))

            @partial(jax.jit,
                     in_shardings=(self.block_edges_sharding,
                                   self.repl_sharding),
                     out_shardings=(self.block_sharding,
                                    self.block_sharding))
            def orient_batch_step(blocks, pos):
                def f(block_local, pos_):
                    lo, hi = jax.vmap(
                        lambda c: elim_ops.orient_edges_pos(c, pos_, n_))(
                            block_local[0])
                    return lo[None], hi[None]
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None, None, None), P()),
                    out_specs=(P(SHARD_AXIS, None, None),
                               P(SHARD_AXIS, None, None)))(blocks, pos)

            # per-execution round budget: the same allowance the
            # per-segment loop would spread over nb segment syncs
            br = max(1, seg_) * nb

            def _fold_batch(P_all, loB_all, hiB_all):
                def f(P_local, loB_local, hiB_local):
                    loB2, hiB2, Pn, sv = elim_ops.batch_segment_fixpoint(
                        P_local[0], loB_local[0], hiB_local[0], n_,
                        lift_levels=lift, batch_rounds=br)
                    # lockstep: every device and process re-dispatches
                    # until the SLOWEST device's block is drained (pmin
                    # of segments-done); rounds/live are pmax'd, retires
                    # psum'd — one replicated word, one host pull
                    done_all = lax.pmin(sv[0], SHARD_AXIS)
                    rounds_mx = lax.pmax(sv[1], SHARD_AXIS)
                    live_mx = lax.pmax(sv[2], SHARD_AXIS)
                    ret_sum = lax.psum(sv[3], SHARD_AXIS)
                    return (Pn[None], loB2[None], hiB2[None],
                            jnp.stack([done_all, rounds_mx, live_mx,
                                       ret_sum]))
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None),
                              P(SHARD_AXIS, None, None),
                              P(SHARD_AXIS, None, None)),
                    out_specs=(P(SHARD_AXIS, None),
                               P(SHARD_AXIS, None, None),
                               P(SHARD_AXIS, None, None), P()))(
                        P_all, loB_all, hiB_all)

            _shardings = dict(
                in_shardings=(self.state_sharding, self.block_sharding,
                              self.block_sharding),
                out_shardings=(self.state_sharding, self.block_sharding,
                               self.block_sharding, self.repl_sharding))

            self.orient_batch_step = orient_batch_step
            self.fold_batch_step = jax.jit(_fold_batch, **_shardings)
            # donated twin: per-device tables + staging blocks alias
            # into the outputs (callers rebind, like the chain driver)
            self.fold_batch_step_donated = jax.jit(
                _fold_batch, donate_argnums=(0, 1, 2), **_shardings)

    SMALL_SIZE = 1 << 14

    def build_step_batch(self, P_all, blocks_dev, pos, stats=None):
        """Fold ``dispatch_batch`` staged sharded batches — a
        (D, N, C, 2) edge block — into the per-device forests with ONE
        replicated stats pull per bounded batched execution (vs one
        ``changed`` pull per segment step in :meth:`build_step`).

        With ``inflight`` > 1, up to that many executions run in flight:
        each speculatively re-dispatches the previous one's output
        blocks before its stats word is pulled (the not-yet-converged
        assumption), and the words are read one-behind. When a pull
        reveals the blocks had drained, the unread speculations are
        discarded — their output is the bit-identical re-confirmation
        of the drained state (all-sentinel rows re-confirm in one round
        each and leave the tables untouched), so adopting the chain tip
        IS resuming from the confirmed carry. Deterministic on the
        replicated word, so every process issues and discards the same
        executions and the collective schedules never skew.

        Scope note: the speculation here is per-GROUP (this method
        still drains before returning), so a group that converges in
        its first execution pays one discarded re-confirm program — a
        deliberate trade: the discard is N cheap all-sentinel rounds,
        the hidden cost is the replicated sv pull's full link RTT (the
        dominant per-group tax on the tunneled chips this targets).
        Cross-group chaining as in the single-device
        fold_segments_pipelined would need the lockstep run() loop
        restructured around a shared chain — left for a future PR."""
        import time

        from collections import deque

        from sheep_tpu.ops.elim import _seed_ms_counters, _t_ms
        from sheep_tpu.utils import fault

        loB, hiB = self.orient_batch_step(blocks_dev, pos)
        fold = self.fold_batch_step_donated if self.donate \
            else self.fold_batch_step
        if stats is not None:
            _seed_ms_counters(stats)
            stats["folded_bytes"] = stats.get("folded_bytes", 0) \
                + int(blocks_dev.size) * 4
        tip = (P_all, loB, hiB)
        fifo: deque = deque()
        idle_since = None
        issued = {"n": 0}

        def issue():
            nonlocal tip, idle_since
            # dispatch-time injection point (ISSUE 9): unwinds the whole
            # group with the donated chain un-drained, like a real
            # allocation failure inside fold(); recoverable kinds only
            # single-process (a one-rank retry would skew collectives)
            issued["n"] += 1
            fault.maybe_fail(
                "dispatch", issued["n"],
                kinds=("oom", "device") if self.procs == 1 else ())
            if idle_since is not None and stats is not None:
                _t_ms(stats, "device_gap_ms",
                      time.perf_counter() - idle_since)
            idle_since = None
            prev = tip
            P2, lo2, hi2, sv = fold(*prev)
            if self.donate:
                # SHEEP_SANITIZE: the chained per-device tables and
                # staging blocks must really be poisoned (metadata-only
                # is_deleted probe, never the dead buffers' contents)
                sanitize.check_donated(
                    *prev,  # sheeplint: donate-ok
                    origin="fold_batch_step_donated")
            tip = (P2, lo2, hi2)
            fifo.append(sv)

        # SHEEP_SANITIZE: between the one-behind replicated word pulls
        # every device value must stay an unread future — a stray sync
        # here would also skew the multi-process collective schedules
        with sanitize.guard("sharded-dispatch"):
            while True:
                while len(fifo) < self.inflight:
                    issue()
                sv = fifo.popleft()
                t_pull = time.perf_counter()
                with sanitize.sync_ok("sharded-sv-pull"):
                    done, r, live, ret = \
                        (int(x) for x in np.asarray(sv))  # sheeplint: sync-ok
                now = time.perf_counter()
                if not fifo:
                    idle_since = now
                if stats is not None:
                    _t_ms(stats, "host_blocked_ms", now - t_pull)
                    stats["host_syncs"] = stats.get("host_syncs", 0) + 1
                    stats["batch_execs"] = \
                        stats.get("batch_execs", 0) + 1
                    stats["batch_retired"] = \
                        stats.get("batch_retired", 0) + ret
                    # max over devices: the lockstep wall is the
                    # slowest one
                    stats["device_rounds"] = \
                        stats.get("device_rounds", 0) + r
                if done >= self.dispatch_batch:
                    if fifo and stats is not None:
                        stats["inflight_discards"] = \
                            stats.get("inflight_discards", 0) + len(fifo)
                    fifo.clear()
                    return tip[0]

    def _fold_actives(self, P_all, lo_all, hi_all, skip_warm: bool = False,
                      stats=None):
        """Adaptive host-driven fold of (D, W) active-constraint buffers
        into the per-device forests (same unique forests as a monolithic
        while_loop): compact every device's buffer to the same smaller
        power-of-2 width when the pmax live count collapses, and run the
        compacted tail in jump-mode (O(C') per round, no O(V)
        lifting-table rebuild). The pmax'd flags keep all devices and
        processes in lockstep; a host tail is not used here because the
        forests are per-device (pulling D of them would cost O(V*D)
        transfers) — the jump-mode tail is the sharded equivalent.
        ``skip_warm`` (merge folds): the buffer was already right-sized
        by the caller, go straight to the resolved schedule. ``stats``
        (if given) accumulates the per-segment lockstep pulls
        (``host_syncs``) and the pmax'd device round count
        (``device_rounds``) — the O(Δ) update-cost instrumentation."""
        size = int(lo_all.shape[-1])
        warm = [] if skip_warm else list(self._fold_warm)
        with sanitize.guard("sharded-fold"):
            while True:
                if warm and size > self.SMALL_SIZE:
                    step = warm.pop(0)
                elif size <= self.SMALL_SIZE:
                    step = self._fold_small
                else:
                    step = self._fold_full
                P_all, lo_all, hi_all, changed, max_live, rounds = step(
                    P_all, lo_all, hi_all)
                # the designed per-segment lockstep pull: one
                # replicated (changed, live) pair per bounded segment
                with sanitize.sync_ok("sharded-segment-pull"):
                    done = not int(changed)  # sheeplint: sync-ok
                    live = int(max_live)  # sheeplint: sync-ok
                    if stats is not None:
                        stats["host_syncs"] = \
                            stats.get("host_syncs", 0) + 1
                        stats["device_rounds"] = \
                            stats.get("device_rounds", 0) \
                            + int(rounds)  # sheeplint: sync-ok
                if done:
                    return P_all
                if size > self.SMALL_SIZE and live <= size // 4:
                    lo_all, hi_all, size = self._compact_to(
                        lo_all, hi_all, live, size)

    def _compact_to(self, lo_all, hi_all, live: int, size: int):
        """Compact (D, size) buffers to the cached power-of-2 program for
        ``2 * live`` (no-op when that is not smaller). One home for the
        capacity rule + program cache shared by the chunk fold and the
        merge's pre-fold right-sizing."""
        new_size = elim_ops.pow2_at_least(2 * live, floor=self.SMALL_SIZE)
        if new_size >= size:
            return lo_all, hi_all, size
        fn = self._compact_cache.get(new_size)
        if fn is None:
            fn = self._compact_cache[new_size] = self._make_compact(new_size)
        lo_all, hi_all = fn(lo_all, hi_all)
        return lo_all, hi_all, new_size

    def build_step(self, P_all, batch_dev, pos, stats=None):
        """Fold one sharded batch into the per-device forests. ``stats``
        (if given) accumulates the fold counters (host_syncs /
        device_rounds via :meth:`_fold_actives`) plus the staged edge
        bytes (``folded_bytes``) — the same cost triple the batched
        path reports, so per-segment builds and delta folds are
        comparable against it."""
        lo_all, hi_all = self.orient_step(batch_dev, pos)
        if stats is not None:
            stats["folded_bytes"] = stats.get("folded_bytes", 0) \
                + int(batch_dev.size) * 4
        return self._fold_actives(P_all, lo_all, hi_all, stats=stats)

    # -- host->device placement (multi-host aware) -------------------------
    def _put(self, sharding, arr):
        """Single process: plain device_put. Multi-host: every process
        passes its process-local rows (or the full array for replicated
        shardings) and JAX assembles the global array. A batch that is
        ALREADY a device array (device-stream synthesis,
        :func:`device_lockstep_batches` — single-process only) relays
        through a device-side device_put: a no-op at the right
        sharding, a D2D re-lay otherwise, never a host crossing."""
        if isinstance(arr, jax.Array):
            return jax.device_put(arr, sharding)
        if self.procs == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(sharding, arr)

    # -- adaptive tree merge (comm point 2) --------------------------------
    def merge(self, P_all, stats: Optional[dict] = None):
        """Merge the per-device forests into the global tree (all in
        position space; callers convert via :func:`to_minp` when they
        need the stable vertex-space encoding).

        Host-driven butterfly: log2(D) rounds, each one jitted exchange
        step (ppermute of the forest — compact boundary pairs or the
        dense table) followed by the shared adaptive fold of the received
        constraints. No unbounded device execution anywhere (the old
        all-in-one-program butterfly ran log2(D) full fixpoints in a
        single call — exactly the long-execution shape that crashes TPU
        worker watchdogs).

        Picks compact (boundary-only pairs) vs dense (full table) shipping
        from one tiny occupancy all-reduce: sparse shards move O(boundary)
        bytes over ICI instead of O(V) per round (SURVEY.md §7 hard part
        #4). Exchange programs are cached per (capacity, round), so at
        most log2(V) * log2(D) exist across a whole run. ``stats`` (if
        given) accumulates the payload byte count actually shipped.
        """
        cap0 = 0
        if self.rounds:
            # one tiny designed all-reduce pull to pick compact vs dense
            with sanitize.sync_ok("merge-occupancy"):
                cnt = int(self.max_occupancy(P_all))  # sheeplint: sync-ok
            c = elim_ops.pow2_at_least(cnt, floor=1024)
            if 2 * c < self.n + 1:
                cap0 = c
        for r in range(self.rounds):
            fn = self._exchange_cache.get((cap0, r))
            if fn is None:
                fn = self._exchange_cache[(cap0, r)] = \
                    self._make_exchange(cap0, r)
            lo_all, hi_all = fn(P_all)
            # received buffers are usually nearly empty (O(boundary)
            # pairs in the exchange's power-of-2 capacity): right-size
            # BEFORE the first fold segment instead of paying one
            # full-width round to discover the live count, and skip the
            # chunk-oriented warm schedule (warm rounds earn their keep
            # on fresh C-width chunks, not on a boundary tail)
            with sanitize.sync_ok("merge-live-count"):
                live = int(self._live_count(lo_all))  # sheeplint: sync-ok
            if live == 0:
                continue
            lo_all, hi_all, _ = self._compact_to(
                lo_all, hi_all, live, int(lo_all.shape[-1]))
            P_all = self._fold_actives(P_all, lo_all, hi_all,
                                       skip_warm=True)
        merged = self._extract_merged(P_all)
        if stats is not None:
            total = 0
            for r in range(self.rounds):
                cap = min(cap0 << r, self.n + 1) if cap0 else self.n + 1
                words = 2 * cap if 2 * cap < self.n + 1 else self.n + 1
                links = sum(1 for i in range(self.n_devices)
                            if (i ^ (1 << r)) < self.n_devices)
                total += 4 * words * links
            stats["merge_payload_bytes"] = \
                stats.get("merge_payload_bytes", 0) + total
            stats["merge_mode"] = "compact" if cap0 else "dense"
        return merged

    # -- state constructors ------------------------------------------------
    def init_degrees(self):
        return self._put(self.state_sharding,
                         np.zeros((self.n_local, self.n + 1), np.int32))

    def init_forest(self):
        return self._put(self.state_sharding,
                         np.full((self.n_local, self.n + 1), self.n, np.int32))

    def put_batch(self, batch: np.ndarray):
        return self._put(self.batch_sharding, batch)

    def put_replicated(self, arr):
        return self._put(self.repl_sharding, np.asarray(arr))

    def _use_byte_range(self, stream) -> bool:
        return use_byte_range(stream, self.procs)

    # -- lockstep batch iteration ------------------------------------------
    def _device_synth(self, stream) -> bool:
        """True when this run ingests by on-device synthesis (ISSUE 12):
        a device stream under a single process. Multi-host keeps the
        host lockstep path (per-process global-array assembly takes
        host rows, and every process must agree on the ingest mode)."""
        return self.procs == 1 and is_device_stream(stream)

    def iter_batches(self, stream, start_chunk: int = 0, stats=None):
        """Process-local lockstep batches (see iter_batches_lockstep):
        host (rows, C, 2) arrays, or pre-placed GLOBAL device batches
        when the input is a device stream (``_put`` relays those
        without a host crossing)."""
        if self._device_synth(stream):
            yield from device_lockstep_batches(
                stream, self.cs, self.n_local, self.n,
                self.batch_sharding, start_chunk=start_chunk,
                stats=stats)
            return
        yield from iter_batches_lockstep(
            stream, self.cs, self.n_local, self.n, self.proc, self.procs,
            start_chunk=start_chunk, byte_range=self._use_byte_range(stream))

    def _staged_batches(self, stream, start_chunk: int = 0, stats=None,
                        group: int = 0):
        """Context-managed batch supplier for the streaming loops:
        prefetch for host-format streams (read/parse/pad overlaps
        device work on a worker thread), :class:`_PassThrough` for
        device-synth streams (buffering global device arrays in a
        worker queue would hold unmodeled HBM, and there is no host
        I/O to overlap). ``group`` > 0 yields lists of up to that many
        batches (the batched dispatch's staging unit)."""
        from sheep_tpu.utils.prefetch import prefetch, prefetch_batched

        it = self.iter_batches(stream, start_chunk=start_chunk,
                               stats=stats)
        if self._device_synth(stream):
            return _PassThrough(_grouped(it, group) if group else it)
        return prefetch_batched(it, group) if group else prefetch(it)

    # -- full run (single process; multi-host callers drive the steps) -----
    def run(self, stream, k: int, alpha: float = 1.0,
            weights: Optional[str] = "unit", comm_volume: bool = False,
            timings: Optional[dict] = None, checkpointer=None,
            resume: bool = False):
        """Drive the whole sharded pipeline over the stream.

        This is the single implementation of the streaming loops; backends
        wrap it and convert the result dict. ``timings`` (if given) is
        filled with per-phase seconds. ``checkpointer`` saves O(V) state
        every ``checkpointer.every`` batches; ``resume`` restarts from it.
        """
        import time

        from sheep_tpu.core import pure
        from sheep_tpu.ops import score as score_ops
        from sheep_tpu.ops.split import tree_split_host
        from sheep_tpu.utils import checkpoint as ckpt
        from sheep_tpu.utils import retry as retry_mod
        from sheep_tpu.utils import watchdog as wd_mod
        from sheep_tpu.utils.fault import maybe_fail

        t = timings if timings is not None else {}
        n, cs, d = self.n, self.cs, self.n_devices
        ckpt_degraded0 = ckpt.degraded_events()
        meta = ckpt.stream_meta(stream, k, cs, weights=weights, alpha=alpha,
                                comm_volume=comm_volume,
                                state_format="sharded", devices=d,
                                procs=self.procs,
                                text_byte_range=self._use_byte_range(stream))
        # multi-host: a fingerprint mismatch must NOT raise per-process
        # here — that would strand the other processes in the reconcile
        # allgather; the sentinel makes reconcile raise collectively
        state = ckpt.resume_state(checkpointer, meta, resume,
                                  raise_on_mismatch=self.procs == 1)
        if self.procs > 1 and checkpointer is not None and resume:
            # per-process manifests may be skewed by one save step; agree
            # on a common step or the collective schedules desynchronize
            state = ckpt.reconcile_multihost_resume(checkpointer, state, meta)
        from_phase = ckpt.phase_index(state.phase) if state else 0

        root_sp = obs.begin("partition", backend="tpu-sharded", k=int(k),
                            n=int(n), devices=int(d),
                            dispatch_batch=int(self.dispatch_batch),
                            inflight=int(self.inflight))
        stats_acc = obs.stats_accumulator()
        merge_acc = obs.stats_accumulator()
        m_cheap = stream.num_edges_cheap
        obs.progress(backend="tpu-sharded", k=int(k), edges_total=m_cheap)

        # ONE build-stats record across the streaming passes, so the
        # ingest counters (device_stream_chunks / h2d_staged_bytes,
        # ISSUE 12) accumulate wherever batches are synthesized
        build_stats: dict = {}
        # out-of-core residency plane (ISSUE 20): under an explicit
        # SHEEP_CACHE_BYTES budget, device batches admitted during the
        # build pass (keyed by absolute chunk index) serve the score
        # pass — and intra-attempt retries — from HBM instead of
        # re-uploading, with checkpoint boundaries as eviction points
        # and spill-before-shrink on RESOURCE faults (_on_resource).
        # Single-process host streams only: device-synth batches have
        # no upload to save, and multi-host residency would skew the
        # collective lockstep.
        rm = None
        if self.procs == 1 and not self._device_synth(stream):
            from sheep_tpu.utils.residency import manager_from_env
            rm = manager_from_env(stats=build_stats)
        # anchored-order inputs (delta: logs, ISSUE 19): the degrees
        # pass streams the BASE segment only — the order anchors to the
        # base degrees exactly as on the single-device backends — while
        # build and score stream the full surviving multiset (the
        # fixpoint is order-independent in the constraint multiset, so
        # the anchored order + full multiset reproduce the single-device
        # table bit for bit). A device-stream base keeps the zero-copy
        # ingest path for the anchor pass.
        anchored = bool(getattr(stream, "order_anchor", False))
        deg_stream = stream.anchor_stream() if anchored else stream
        # pass 1: degrees, int32 on device with int64 host flushes so no
        # per-vertex endpoint count can reach 2^31 between flushes
        t0 = time.perf_counter()
        sp = obs.begin("degrees+sort")
        obs.progress(phase="degrees", chunks_done=0, edges_done=0)
        flush_every = max(1, (2**31 - 1) // max(2 * cs * d, 1))
        if state:
            deg_host = state.arrays["deg"].copy()
        else:
            deg_host = np.zeros(n, dtype=np.int64)
        if from_phase == 0:
            start = state.chunk_idx if state else 0
            deg_all = self.init_degrees()
            since = batches = 0
            with wd_mod.watched(self.procs, "sharded-degrees",
                                self.proc) as wd, \
                    self._staged_batches(deg_stream, start,
                                         build_stats) as pf:
                # with-exit = deterministic worker cancel on exception
                # unwind (fault injection, checkpoint IO)
                for batch in pf:
                    deg_all = self.deg_step(deg_all, self.put_batch(batch))
                    since += 1
                    batches += 1
                    wd.touch(f"degrees batch {batches}")
                    maybe_fail("degrees", batches, kinds=("kill", "stall"))
                    obs.chunk_progress(batches * d, cs, m_cheap)
                    # cadence is in *chunks* (one batch = d chunks),
                    # matching the single-device backends and the
                    # --checkpoint-every doc
                    at_ckpt = (checkpointer is not None and
                               checkpointer.due_span((batches - 1) * d,
                                                     batches * d))
                    if since >= flush_every or at_ckpt:
                        deg_host += np.asarray(  # sheeplint: sync-ok
                            self.deg_reduce(deg_all)[:n], dtype=np.int64)
                        deg_all = self.init_degrees()
                        since = 0
                    if at_ckpt:
                        checkpointer.save("degrees", start + batches * d,
                                          {"deg": deg_host}, meta)
            deg_host += np.asarray(  # sheeplint: sync-ok
                self.deg_reduce(deg_all)[:n], dtype=np.int64)
        # positions are ordinal: rank-compress if totals exceed int32
        if deg_host.size and deg_host.max() >= 2**31:
            deg_rank = np.argsort(np.argsort(deg_host, kind="stable"),
                                  kind="stable")
        else:
            deg_rank = deg_host
        deg_total = self.put_replicated(
            np.concatenate([deg_rank, [0]]).astype(np.int32))
        pos, order = self.make_order(deg_total)
        pos.block_until_ready()
        t["degrees+sort"] = time.perf_counter() - t0
        sp.end()

        # pass 2: per-device forests, then butterfly merge (comm point 2).
        # Device state is position-space (P tables); checkpoints and the
        # returned forest keep the stable vertex-space minp encoding, so
        # conversions (one replicated gather each way) happen only at
        # checkpoint/phase boundaries.
        t0 = time.perf_counter()
        sp = obs.begin("build+merge")
        obs.progress(phase="build", chunks_done=0, edges_done=0)
        merge_stats: dict = {}
        # fault kinds the per-batch injection points can absorb: the
        # in-process retry below only runs single-process (a one-rank
        # retry would desynchronize the collective schedules), so chaos
        # only offers the recoverable kinds there; multi-host points
        # offer kill (the PR-8 contract) and stall (the watchdog's prey)
        bkinds = ("kill", "oom", "device", "stall") if self.procs == 1 \
            else ("kill", "stall")
        if state and from_phase >= 2:
            merged_minp = jnp.asarray(state.arrays["merged"])
        else:
            # fault-tolerant build (ISSUE 9): one retryable attempt
            # against an in-memory snapshot — the merged O(V) forest +
            # next chunk index, exactly a checkpoint's payload, banked
            # at every save. Build checkpoints store the O(V) *merged*
            # forest, not the O(V*d) per-device stack; merging is
            # associative and idempotent, so re-seeding one shard with
            # it (others empty) reproduces the identical fixpoint.
            # Multi-host: each process provides its local rows; the
            # merged forest rides in global row 0 (process 0).
            snap = {"idx": 0, "merged": None}
            if state and state.phase == "build":
                snap["idx"] = state.chunk_idx
                snap["merged"] = state.arrays["merged_partial"]

            def _build_attempt():
                rows = self.n_local
                fa = np.full((rows, n + 1), n, np.int32)
                if snap["merged"] is not None and self.proc == 0:
                    # vertex-space snapshot -> position space, host-side
                    # (no device round-trip, no eager op on a global
                    # array)
                    fa[0] = np.asarray(  # sheeplint: sync-ok
                        snap["merged"],
                        dtype=np.int32)[np.asarray(order)]  # sheeplint: sync-ok
                P_all = self._put(self.state_sharding, fa)
                start = snap["idx"]
                batches = 0
                with wd_mod.watched(self.procs, "sharded-build",
                                    self.proc) as wd:
                    if self.dispatch_batch > 1 or self.inflight > 1:
                        # batched segment dispatch: stage dispatch_batch
                        # sharded batches as one (rows, N, C, 2) block
                        # per process — the prefetch worker groups the
                        # lockstep batch stream, so every process stages
                        # identical groups and the pmin'd stats keep the
                        # collective schedules aligned
                        nb = self.dispatch_batch
                        build_stats["dispatch_batch"] = nb
                        build_stats["inflight_depth"] = self.inflight
                        empty = None
                        devsynth = self._device_synth(stream)
                        # with-exit = deterministic worker cancel on an
                        # exception unwind (fault injection, checkpoint
                        # IO), as in _device_chunk_groups
                        with self._staged_batches(stream, start,
                                                  build_stats,
                                                  group=nb) as pf:
                            for group in pf:
                                gl = len(group)
                                if gl < nb:
                                    if empty is None:
                                        # device-synth groups pad with a
                                        # device-resident sentinel batch
                                        # (no host block to upload)
                                        empty = jnp.full(
                                            (self.n_local, cs, 2), n,
                                            jnp.int32) if devsynth \
                                            else np.full(
                                                (self.n_local, cs, 2),
                                                n, np.int32)
                                    group = group + [empty] * (nb - gl)
                                blocks = jnp.stack(group, axis=1) \
                                    if devsynth \
                                    else np.stack(group, axis=1)
                                before = batches
                                dsp = obs.begin("dispatch", i=before,
                                                batches=gl)
                                try:
                                    P_all = self.build_step_batch(
                                        P_all,
                                        self._put(
                                            self.block_edges_sharding,
                                            blocks),
                                        pos, stats=build_stats)
                                finally:
                                    stats_acc.absorb(build_stats)
                                    dsp.end()
                                batches += gl
                                wd.touch(f"build batch {batches}")
                                obs.chunk_progress(batches * d, cs,
                                                   m_cheap)
                                for b in range(before + 1, batches + 1):
                                    maybe_fail("build", b, kinds=bkinds)
                                if checkpointer is not None and \
                                        checkpointer.due_span(
                                            before * d, batches * d):
                                    partial = np.asarray(self.to_minp(  # sheeplint: sync-ok
                                        self.merge(P_all,
                                                   stats=merge_stats),
                                        pos))
                                    snap["idx"] = start + batches * d
                                    snap["merged"] = partial
                                    checkpointer.save(
                                        "build", start + batches * d,
                                        {"deg": deg_host,
                                         "merged_partial": partial},
                                        meta)
                    else:
                        with self._staged_batches(stream, start,
                                                  build_stats) as pf:
                            for batch in pf:
                                seg_sp = obs.begin("segment", i=batches)
                                try:
                                    key = start + batches * d
                                    dev_batch = rm.get(key) \
                                        if rm is not None else None
                                    if dev_batch is None:
                                        dev_batch = self.put_batch(batch)
                                        if rm is not None:
                                            rm.admit(key, dev_batch,
                                                     int(batch.nbytes))
                                    P_all = self.build_step(
                                        P_all, dev_batch,
                                        pos, stats=build_stats)
                                finally:
                                    seg_sp.end()
                                batches += 1
                                wd.touch(f"build batch {batches}")
                                obs.chunk_progress(batches * d, cs,
                                                   m_cheap)
                                maybe_fail("build", batches,
                                           kinds=bkinds)
                                if checkpointer is not None and \
                                        checkpointer.due_span(
                                            (batches - 1) * d,
                                            batches * d):
                                    partial = np.asarray(self.to_minp(  # sheeplint: sync-ok
                                        self.merge(P_all,
                                                   stats=merge_stats),
                                        pos))
                                    snap["idx"] = start + batches * d
                                    snap["merged"] = partial
                                    checkpointer.save(
                                        "build", start + batches * d,
                                        {"deg": deg_host,
                                         "merged_partial": partial},
                                        meta)
                                    if rm is not None:
                                        # checkpoint boundary = eviction
                                        # point: retries never re-read
                                        # behind the confirmed index
                                        rm.boundary(start + batches * d)
                return P_all

            def _on_resource():
                nxt = retry_mod.degrade_dispatch(
                    n, cs, self.dispatch_batch, self.inflight,
                    self.donate, build_stats, snap["idx"],
                    residency=rm)
                if nxt is not None:
                    self.dispatch_batch, self.inflight = nxt

            def _save_snapshot():
                if checkpointer is not None and \
                        snap["merged"] is not None:
                    checkpointer.save(
                        "build", snap["idx"],
                        {"deg": deg_host,
                         "merged_partial": snap["merged"]}, meta)

            def _on_device_loss():
                retry_mod.recover_device_loss(build_stats, snap["idx"],
                                              _save_snapshot)

            policy = retry_mod.RetryPolicy()
            while True:
                try:
                    P_all = _build_attempt()
                    break
                except Exception as exc:
                    if self.procs > 1:
                        # a one-rank in-process retry would skew the
                        # collective schedules: multi-host keeps the
                        # fault->checkpoint->kill+resume contract
                        raise
                    # shared classify/budget/count/backoff protocol
                    # (retry.handle_build_fault); FATAL and exhausted
                    # budgets re-raise inside
                    retry_mod.handle_build_fault(
                        policy, exc, "sharded.build", build_stats,
                        on_resource=_on_resource,
                        on_device_loss=_on_device_loss)
                    stats_acc.absorb(build_stats)
            msp = obs.begin("merge", devices=int(d))
            merged_minp = self.to_minp(
                self.merge(P_all, stats=merge_stats), pos)
            # real completion barrier
            np.asarray(merged_minp[:1])  # sheeplint: sync-ok
            merge_acc.absorb(merge_stats)
            msp.end()
        t["build+merge"] = time.perf_counter() - t0
        stats_acc.absorb(build_stats)
        sp.end()

        # split on host over O(V) state
        t0 = time.perf_counter()
        with obs.span("split"):
            parent = elim_ops.minp_to_parent(merged_minp, order, n)
            pos_host = np.asarray(pos[:n])  # sheeplint: sync-ok
            w = deg_host.astype(np.float64) if weights == "degree" else None
            assign_host = tree_split_host(parent, pos_host, k, weights=w,
                                          alpha=alpha)
            assign = self.put_replicated(
                np.concatenate([assign_host.astype(np.int32),
                                np.zeros(1, np.int32)]))
            t["split"] = time.perf_counter() - t0

        # pass 3: scoring (comm point 3)
        t0 = time.perf_counter()
        sp = obs.begin("score")
        obs.progress(phase="score", chunks_done=0, edges_done=0)
        cut = total = 0
        cv_chunks = []
        start = 0
        if state and state.phase == "score":
            start = state.chunk_idx
            cut = int(state.arrays["cut"])
            total = int(state.arrays["total"])
            if comm_volume:
                cv_chunks.append(state.arrays["cv_keys"])
        batches = 0
        with wd_mod.watched(self.procs, "sharded-score",
                            self.proc) as wd, \
                self._staged_batches(stream, start, build_stats) as pf:
            for batch in pf:
                key = start + batches * d
                dev_batch = rm.get(key) if rm is not None else None
                if dev_batch is None:
                    dev_batch = self.put_batch(batch)
                    if rm is not None:
                        rm.admit(key, dev_batch, int(batch.nbytes))
                c, tt = np.asarray(  # sheeplint: sync-ok
                    self.score_step(dev_batch, assign))
                cut += int(c)
                total += int(tt)
                if comm_volume:
                    score_ops.accumulate_cv_keys(
                        cv_chunks,
                        score_ops.cut_pair_keys_host(batch, assign, n, k))
                batches += 1
                wd.touch(f"score batch {batches}")
                maybe_fail("score", batches, kinds=("kill", "stall"))
                obs.chunk_progress(batches * d, cs, m_cheap)
                if checkpointer is not None and \
                        checkpointer.due_span((batches - 1) * d,
                                              batches * d):
                    cv_chunks = ckpt.save_score_state(
                        checkpointer, start + batches * d, cut, total,
                        cv_chunks,
                        {"deg": deg_host,
                         "merged": np.asarray(merged_minp)},  # sheeplint: sync-ok
                        meta, comm_volume)
                    if rm is not None:
                        rm.boundary(start + batches * d)
        cv = None
        if comm_volume:
            keys = ckpt.compact_cv_keys(cv_chunks)
            if self.procs > 1:
                # each process saw only its shard's cut edges: union the
                # per-host key sets (padded allgather, then host unique)
                from jax.experimental import multihost_utils

                lens = multihost_utils.process_allgather(
                    np.array([len(keys)], np.int64))
                mx = max(1, int(lens.max()))
                pad = np.full(mx, -1, np.int64)
                pad[:len(keys)] = keys
                allk = multihost_utils.process_allgather(pad)
                keys = np.unique(allk[allk >= 0])
            cv = int(len(keys))
        balance = pure.part_balance(assign_host, k,
                                    deg_host if weights == "degree" else None)
        t["score"] = time.perf_counter() - t0
        sp.end()
        root_sp.end()
        if checkpointer is not None:
            checkpointer.clear()
        if ckpt.degraded_events() > ckpt_degraded0:
            build_stats["checkpoint_degraded"] = \
                ckpt.degraded_events() - ckpt_degraded0
        return {
            "assignment": assign_host, "parent": parent, "pos": pos_host,
            "degrees": deg_host, "edge_cut": cut, "total_edges": total,
            "balance": balance, "comm_volume": cv, "k": k,
            "merge_stats": merge_stats, "build_stats": build_stats,
        }


# ---------------------------------------------------------------------------
# process-wide compiled-pipeline cache (ISSUE 19)
# ---------------------------------------------------------------------------
# Every ShardedPipeline() re-traces and re-compiles the whole per-shard
# program set (deg/orient/fold/merge/score close over n, the chunk shape
# and the shardings) — ~1.7 s per instance on the 8-way virtual mesh,
# paid per backend instance regardless of graph size. The pipeline is
# stateless across runs except the lazy program caches we WANT to share
# and ONE degrade path: a resource fault inside run() permanently lowers
# self.dispatch_batch/self.inflight, so a cache hit re-checks those
# against the requested shape and rebuilds if a prior run degraded them.
# Keyed on the full constructor signature plus the mesh's device ids;
# bounded LRU so long-lived processes don't pin dead programs.

_PIPE_CACHE: "OrderedDict[tuple, ShardedPipeline]" = OrderedDict()
_PIPE_CACHE_MAX = 24


def cached_pipeline(n: int, chunk_edges: int, mesh, lift_levels: int = 0,
                    segment_rounds: int = 32, warm_schedule=((1, 8),),
                    dispatch_batch: int = 1, inflight: int = 1,
                    donate: bool = False) -> ShardedPipeline:
    """ShardedPipeline with its compiled programs reused across backend
    instances (one-shot builds, resident epoch folds, compaction
    rebuilds — all hit the same programs for the same shape)."""
    key = (n, chunk_edges, tuple(d.id for d in mesh.devices.flat),
           lift_levels, segment_rounds, tuple(warm_schedule),
           max(1, int(dispatch_batch)), int(inflight), bool(donate))
    pipe = _PIPE_CACHE.get(key)
    if pipe is not None and (pipe.dispatch_batch != key[6]
                             or pipe.inflight != key[7]):
        del _PIPE_CACHE[key]  # degraded by a prior run's fault path
        pipe = None
    if pipe is None:
        pipe = ShardedPipeline(n, chunk_edges, mesh,
                               lift_levels=lift_levels,
                               segment_rounds=segment_rounds,
                               warm_schedule=warm_schedule,
                               dispatch_batch=dispatch_batch,
                               inflight=inflight, donate=donate)
        _PIPE_CACHE[key] = pipe
        while len(_PIPE_CACHE) > _PIPE_CACHE_MAX:
            _PIPE_CACHE.popitem(last=False)
    else:
        _PIPE_CACHE.move_to_end(key)
    return pipe
