"""Vertex-sharded build for graphs whose tables exceed one chip
(SURVEY.md §7 hard part #2; BASELINE.md eval config 5, RMAT-30 class).

The standard sharded pipeline replicates the O(V) pos/order tables and
keeps one forest per device, so 8 chips raise edge throughput but not the
vertex ceiling (2^29 on 16 GiB). This pipeline shards every vertex-indexed
table into contiguous blocks of B = ceil((V+1)/D) rows — device d owns
global rows [dB, (d+1)B) — cutting per-device table memory to O(V/D):
RMAT-30 (V=2^30) fits a v5e-8 slice at ~2.6 GiB/chip.

With the displacement fixpoint (ops/elim.py) the build needs no partial
trees and no merge at all: there is ONE distributed forest table, and all
devices' active constraints fold into it concurrently through routed
collective ops. Like the single-chip path, the fixpoint runs in
POSITION SPACE: the forest table P is indexed by elimination position
(block-sharded by position), actives are (loP, hiP) position pairs, and
a climb step is one routed P-lookup — the vertex-space formulation
needed a second routed order[] lookup per step and carried the vertex
id alongside, so position space halves the climb collectives AND drops
a third of the active-buffer traffic. Per fixpoint round (inside
shard_map over the ``shards`` axis):

  1. routed scatter-min  — all_gather the (loP, hiP) requests; each
     owner folds the requests hitting its block into its P shard and
     answers (pre-round, post-round) parent positions; answers ride one
     all_to_all back and combine with jnp.min (non-owners answer the
     sentinel n = +inf).
  2. routed gather       — P[p] lookups for the climb (``jumps``
     single-step climbs per round instead of the single-chip path's
     binary-lifting tables, which would be V-sized).
  3. local rewrite       — retire / displace-in-place / climb, exactly
     the single-chip displacement rules; liveness is a psum, so the
     while_loop terminates collectively.

The elimination order is computed on HOST (one stable numpy argsort
over the degree table — hosts hold hundreds of GB; one sort per run,
amortized
over the whole stream) and only the pos block shard is pushed to
devices (position space needs no device-side order table). The split
likewise runs on host over the O(V) parent array (native C++).
Degrees accumulate into a block-sharded table via the same
routed scatter pattern, and scoring resolves part lookups against a
block-sharded assignment table with the routed gather — NO vertex-indexed
device state is replicated anywhere in the pipeline, so per-device memory
really is O(V/D) tables + O(D * chunk) routing buffers. (Host memory is
O(V): the degree fold, sort, and split run there by design.)

The fixpoint loop is driven from the HOST in bounded segments
(``segment_rounds`` rounds per device execution): long single accelerator
executions are what crash TPU worker watchdogs, and the collective
``live`` count makes every device (and every process) agree on the
segment boundary, so the lockstep host loop is safe under shard_map.

Everything is static-shape: routing buffers are (D, Q) for Q actives, so
there are no per-destination capacity constants and no overflow paths —
the cost is shipping D*Q words per collective, the standard trade for
hub-skewed (power-law) graphs where per-owner request counts are
unboundedly uneven.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheep_tpu import obs
from sheep_tpu.analysis import sanitize
from sheep_tpu.io.devicestream import is_device_stream
from sheep_tpu.ops.elim import pow2_at_least
from sheep_tpu.parallel.mesh import SHARD_AXIS, shard_map


class BigVPipeline:
    """Compiled vertex-sharded pipeline for a fixed (n, chunk_edges, mesh).

    ``jumps`` = single-step parent climbs per TAIL-phase round (bulk
    rounds use stream-descent lifting with ``lift_levels`` tables — see
    ``_make_fold_lift``); more tail jumps = fewer tail rounds at ~flat
    collective bytes.
    """

    def __init__(self, n: int, chunk_edges: int, mesh, jumps: int = 128,
                 max_rounds: int = 1 << 20, segment_rounds: int = 16,
                 dedup_compact: bool = True, lift_levels: int = 0,
                 hoist_bytes: Optional[int] = None):
        d = mesh.devices.size
        self.n = n
        self.cs = chunk_edges
        self.mesh = mesh
        self.n_devices = d
        self.jumps = jumps
        self.B = -(-(n + 1) // d)  # owned rows per device
        self.rows = d * self.B      # padded global table length
        self.segment_rounds = segment_rounds
        # multi-host: same collectives ride DCN; this process owns
        # n_local contiguous mesh rows (jax.devices() orders by process),
        # so its local span of any block-sharded table is
        # [proc * n_local * B, (proc+1) * n_local * B)
        self.dedup_compact = dedup_compact
        # bulk-phase stream-descent lifting depth (0 = auto: enough to
        # cover any ancestor chain in one round, like single-chip)
        self.lift_levels = lift_levels if lift_levels > 0 \
            else max(1, int(n).bit_length())
        # hoisted-stack HBM budget per device (stale lifting tables,
        # _make_fold_lift_hoisted): each hoisted level keeps one B-row
        # int32 block alive for the whole segment. Default 0 = per-round
        # squaring: MEASURED at RMAT-16/D=8 (tools/bigv_collectives.py),
        # hoisting LOST — q_rounds 1.06M -> 2.1M, 1540 -> 2651 MB/device
        # — because 16 rounds of stack staleness delay the live-set
        # collapse at bulk width, while the squaring term it amortizes
        # is only V words/round (small next to D*Q lookups when V << Q).
        # The trade can only reverse in the V-dominant regime (B >> Q,
        # the RMAT-30 class); enable there explicitly via hoist_bytes /
        # SHEEP_BIGV_HOIST_BYTES and re-measure (BASELINE.md bigv).
        # env is a fallback for the DEFAULT only; an explicit ctor value
        # always wins (review finding: an exported experiment var must
        # not silently override TpuBigVBackend(hoist_bytes=X))
        import os as _os

        self.hoist_bytes = hoist_bytes if hoist_bytes is not None \
            else int(_os.environ.get("SHEEP_BIGV_HOIST_BYTES", "0"))
        self.hoist_levels = min(self.lift_levels - 1,
                                max(0, self.hoist_bytes // (4 * self.B)))
        self.procs = len({dev.process_index for dev in mesh.devices.flat})
        self.proc = jax.process_index() if self.procs > 1 else 0
        self.n_local = (sum(1 for dev in mesh.devices.flat
                            if dev.process_index == jax.process_index())
                        if self.procs > 1 else d)
        if self.procs > 1 and self.n_local * self.procs != d:
            raise ValueError("uneven devices per process not supported")

        self.shard = NamedSharding(mesh, P(SHARD_AXIS))        # (rows,)
        self.batch_sharding = NamedSharding(mesh, P(SHARD_AXIS, None, None))
        self.repl = NamedSharding(mesh, P())

        n_, B, D = self.n, self.B, d

        # ---- routed primitives (shard_map bodies) ------------------------

        def _lookup(table_local, q):
            """table[q] for arbitrary global ids q (Q,) against a
            block-sharded table; sentinel-safe (answers n for q >= rows
            handled by the ownership mask; q == n hits the padded
            sentinel row, which every shard keeps at value n)."""
            gq = lax.all_gather(q, SHARD_AXIS)          # (D, Q)
            me = lax.axis_index(SHARD_AXIS)
            local = gq - me * B
            ok = (local >= 0) & (local < B)
            part = jnp.where(ok, table_local[jnp.clip(local, 0, B - 1)],
                             jnp.int32(n_))
            mine = lax.all_to_all(part, SHARD_AXIS, 0, 0)
            return jnp.min(mine, axis=0)                # (Q,)

        def _scatter_min(table_local, lo, val):
            """Fold (lo -> val) requests from EVERY device into the
            distributed table; returns (new_table_local, old, new) where
            old/new are the pre-/post-round parent positions at each of
            THIS device's requests."""
            glo = lax.all_gather(lo, SHARD_AXIS)        # (D, Q)
            gval = lax.all_gather(val, SHARD_AXIS)
            me = lax.axis_index(SHARD_AXIS)
            local = glo - me * B
            ok = (local >= 0) & (local < B)
            idx = jnp.where(ok, local, B)               # B = dropped
            new_local = table_local.at[idx.ravel()].min(
                gval.ravel(), mode="drop")
            lidx = jnp.clip(local, 0, B - 1)
            old_part = jnp.where(ok, table_local[lidx], jnp.int32(n_))
            new_part = jnp.where(ok, new_local[lidx], jnp.int32(n_))
            old = jnp.min(lax.all_to_all(old_part, SHARD_AXIS, 0, 0), axis=0)
            new = jnp.min(lax.all_to_all(new_part, SHARD_AXIS, 0, 0), axis=0)
            return new_local, old, new

        # ---- degrees: block-sharded accumulator, routed scatter-add -----
        # (same ownership routing as _scatter_min; semantics match
        # ops/degrees.degree_chunk: clip to [0, n], slot n absorbs padding,
        # self-loops count twice)
        @partial(jax.jit, out_shardings=self.shard)
        def deg_zeros():
            return jnp.zeros(self.rows, jnp.int32)

        @partial(jax.jit,
                 in_shardings=(self.shard, self.batch_sharding),
                 out_shardings=self.shard)
        def deg_step(deg_sh, batch):
            def f(deg_local, chunk_local):
                ids = jnp.clip(chunk_local[0].reshape(-1), 0, n_) \
                    .astype(jnp.int32)
                gids = lax.all_gather(ids, SHARD_AXIS)      # (D, 2C)
                me = lax.axis_index(SHARD_AXIS)
                local = gids - me * B
                idx = jnp.where((local >= 0) & (local < B), local, B)
                return deg_local.at[idx.ravel()].add(1, mode="drop")
            return shard_map(f, mesh=mesh,
                             in_specs=(P(SHARD_AXIS),
                                       P(SHARD_AXIS, None, None)),
                             out_specs=P(SHARD_AXIS))(deg_sh, batch)

        # ---- the routed displacement fixpoint ---------------------------
        act = NamedSharding(mesh, P(SHARD_AXIS, None))  # (D, Q) actives

        @partial(jax.jit,
                 in_shardings=(self.shard, self.batch_sharding),
                 out_shardings=(act, act))
        def orient_step(pos_sh, batch):
            """Resolve a batch's endpoints to oriented POSITION-PAIR
            constraints (loP, hiP); loop detection is local
            (loP == hiP -> inert)."""
            def f(pos_local, chunk_local):
                chunk = chunk_local[0]
                u = jnp.clip(chunk[:, 0], 0, n_)
                v = jnp.clip(chunk[:, 1], 0, n_)
                pu = _lookup(pos_local, u)
                pv = _lookup(pos_local, v)
                lo = jnp.minimum(pu, pv).astype(jnp.int32)
                hi = jnp.maximum(pu, pv).astype(jnp.int32)
                bad = (pu == pv) | (pu == n_) | (pv == n_)
                lo = jnp.where(bad, n_, lo)
                hi = jnp.where(bad, n_, hi)
                return lo[None], hi[None]
            return shard_map(
                f, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS, None, None)),
                out_specs=(P(SHARD_AXIS, None),) * 2)(pos_sh, batch)

        seg_ = self.segment_rounds

        def _make_fold(climb, prepare=None):
            """Segment program factory: at most ``segment_rounds`` routed
            fixpoint rounds in one device execution; the psum'd live
            count is the collective continue signal, identical on every
            device/process, so the host loop segment boundaries stay in
            lockstep. Retire/displace semantics match the single-chip
            _pos_small_round_body with the table lookups routed; the ONE
            varying piece is ``climb(ctx, P_l, cur, hi_) -> cur`` — built
            by :func:`_make_fold_seg` (fixed jump count) or
            :func:`_make_fold_lift` (stream-descent lifting) so the two
            kernels cannot drift apart. ``prepare(P_local) -> ctx`` runs
            ONCE per segment before the round loop (hoisted lifting
            stacks); its outputs enter the while_loop as constants."""

            @partial(jax.jit,
                     in_shardings=(self.shard, act, act),
                     out_shardings=(self.shard, act, act, self.repl,
                                    self.repl, self.repl))
            def fold_seg_step(P_sh, lo_all, hi_all):
                def f(P_local, lo_l, hi_l):
                    lo0, hi0 = lo_l[0], hi_l[0]
                    ctx = prepare(P_local) if prepare is not None else None

                    def body(state):
                        lo_, hi_, P_l, _, rounds = state
                        P_l, old, new = _scatter_min(P_l, lo_, hi_)

                        retire = hi_ == new
                        displaced = retire & (new < old) & (old < n_)

                        # climb: first step from the scatter reply, the
                        # rest from the pluggable climb body
                        can0 = new < hi_
                        cur = jnp.where(can0, new, lo_)
                        cur = climb(ctx, P_l, cur, hi_)
                        became_loop = cur == hi_
                        climb_lo = jnp.where(became_loop, n_, cur)
                        climb_hi = jnp.where(became_loop, n_, hi_)

                        # displaced constraint: (new, old-parent pos)
                        out_lo = jnp.where(
                            retire, jnp.where(displaced, new, n_),
                            climb_lo).astype(jnp.int32)
                        out_hi = jnp.where(
                            retire, jnp.where(displaced, old, n_),
                            climb_hi).astype(jnp.int32)
                        live = lax.psum(jnp.sum(out_lo != n_), SHARD_AXIS)
                        return out_lo, out_hi, P_l, live, rounds + 1

                    def cond(state):
                        _, _, _, live, rounds = state
                        return (live > 0) & (rounds < seg_)

                    live0 = lax.psum(jnp.sum(lo0 != n_), SHARD_AXIS)
                    state = (lo0, hi0, P_local, live0,
                             (live0 * 0).astype(jnp.int32))
                    lo_f, hi_f, P_f, live_f, rounds = \
                        lax.while_loop(cond, body, state)
                    max_live = lax.pmax(jnp.sum(lo_f != n_), SHARD_AXIS)
                    return (P_f, lo_f[None], hi_f[None],
                            live_f, lax.pmax(rounds, SHARD_AXIS), max_live)

                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS),
                              P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS, None),
                               P(SHARD_AXIS, None), P(), P(), P()))(
                        P_sh, lo_all, hi_all)

            return fold_seg_step

        def _make_fold_seg(jumps_n: int):
            """Fold program with ``jumps_n`` single-step climbs per round
            — the TAIL regime: a displacement cascade of length l costs
            ~l/j rounds but ~2*l*D*Q collective words regardless of j,
            so at small Q more jumps cut rounds (and per-op collective
            latencies) nearly for free (measured: BASELINE.md bigv
            entry)."""

            def climb(ctx, P_l, cur, hi_):
                for _ in range(jumps_n - 1):
                    p_next = _lookup(P_l, cur)
                    cur = jnp.where(p_next < hi_, p_next, cur)
                return cur

            return _make_fold(climb)

        def _make_fold_lift(levels_n: int):
            """Fold program whose climb uses STREAM-DESCENT BINARY
            LIFTING on the distributed table — the single-chip trick
            (ops/elim.py stream descent: square ONE table in place,
            t <- t[t], interleaved with jumps) carried to the
            block-sharded layout, for the BULK regime. A squaring is a
            routed lookup at the OWNED-rows width B = V/D (D*B = V words
            per device), *cheaper* than one jump collective at full Q —
            and lifting collapses the round count the way it does on one
            chip (measured: 430 jump rounds -> 31 lift rounds at
            RMAT-15/D=8 with ~6x less total traffic, BASELINE.md).
            Memory stays O(V/D): exactly one extra table block lives at
            a time. Every taken jump lands on a genuine ancestor still
            earlier than hi, so each rewrite is sound and the unique
            fixpoint is unchanged."""

            def climb(ctx, P_l, cur, hi_):
                t = P_l
                for j in range(levels_n):
                    cand = _lookup(t, cur)
                    cur = jnp.where(cand < hi_, cand, cur)
                    if j < levels_n - 1:
                        t = _lookup(t, t)   # routed squaring (width B)
                return cur

            return _make_fold(climb)

        def _make_fold_lift_hoisted(levels_n: int, hoist_n: int):
            """:func:`_make_fold_lift` with the squared tables HOISTED
            out of the round loop — the bigv port of the single-chip
            stale-tables trick (ops/elim.py fold_segment_pos_hoisted).
            The per-round climb above re-squares the table every round:
            2*(levels-1) routed B-width collectives shipping ~V words
            per device PER ROUND — the dominant V-term of the bulk
            phase (BASELINE.md bigv entry: 'the remaining question at
            RMAT-30 scale is the V-word squaring term'). Here the stack
            of ``hoist_n`` squared tables is built ONCE per segment
            (stale between rounds; level 0 = the live table stays
            current), so the squaring traffic amortizes over
            ``segment_rounds`` rounds. Sound for the same reason as the
            single-chip variant: ancestor-ship is permanent, so a stale
            jump lands on a genuine (possibly non-maximal) ancestor; the
            fixpoint exit stays exact because the segment loop only
            exits on live == 0 (all constraints retired), which is
            table-freshness-independent. ``hoist_n`` < levels-1 caps the
            stack's HBM at hoist_bytes (scale-30 tables cannot afford a
            full log2(V) stack per device); shorter reach just means a
            long cascade takes extra (cheap, stackless) rounds."""

            def prepare(P_local):
                stack = []
                t = P_local
                for _ in range(hoist_n):
                    t = _lookup(t, t)   # routed squaring (width B)
                    stack.append(t)
                return tuple(stack)

            def climb(stack, P_l, cur, hi_):
                cand = _lookup(P_l, cur)        # level 0: CURRENT table
                cur = jnp.where(cand < hi_, cand, cur)
                for t in stack:                 # stale hoisted levels
                    cand = _lookup(t, cur)
                    cur = jnp.where(cand < hi_, cand, cur)
                # reach beyond the byte-capped stack: keep squaring
                # dynamically from the deepest hoisted table (per-round
                # cost returns, but only for the levels past the cap)
                t = stack[-1] if stack else P_l
                for _ in range(hoist_n + 1, levels_n):
                    t = _lookup(t, t)
                    cand = _lookup(t, cur)
                    cur = jnp.where(cand < hi_, cand, cur)
                return cur

            return _make_fold(climb, prepare=prepare)

        def _make_compact(to_size: int):
            """Dedup + pack each device's live (loP, hiP) actives into a
            (D, to_size) buffer (valid when every device's live count <=
            to_size — the caller checks the pmax). Shrinking Q directly
            shrinks every routed collective: all_gather/all_to_all ship
            D * Q words per round.

            The dedup (drop duplicate (lo, hi) pairs via one 2-key sort,
            exactly like the single-chip ``compact_actives(dedup=True)``)
            is the "dedup requests before the all_gather" lever: after a
            few rounds many slots have been rewritten to the same
            (ancestor, hi) constraint — on hub-skewed graphs MOST of
            them (a star graph's requests all climb to the hub). The
            constraint closure is a SET property (duplicates retire
            together and spawn identical displacements), so dropping
            in-shard duplicates is exact; cross-shard duplicates remain
            (deduping them would need an extra routed pass). Runs only
            at compaction cadence, not per round — a per-round sort was
            measured in seconds at C=2^24 on the v5e (BASELINE.md)."""
            act = NamedSharding(mesh, P(SHARD_AXIS, None))

            dedup = self.dedup_compact

            @partial(jax.jit,
                     in_shardings=(act, act),
                     out_shardings=(act, act))
            def compact_step(lo_all, hi_all):
                def f(lo_l, hi_l):
                    lo0, hi0 = lo_l[0], hi_l[0]
                    if dedup:
                        lo0, hi0 = lax.sort((lo0, hi0), num_keys=2)
                        dup = (lo0 == jnp.roll(lo0, 1)) & \
                            (hi0 == jnp.roll(hi0, 1))
                        dup = dup.at[0].set(False)
                        lo0 = jnp.where(dup, n_, lo0)
                        hi0 = jnp.where(dup, n_, hi0)
                    c = lo0.shape[0]
                    sel = jnp.nonzero(lo0 != n_, size=to_size,
                                      fill_value=c)[0]
                    ext = lambda a: jnp.concatenate(
                        [a, jnp.full(1, n_, a.dtype)])[sel]
                    return (ext(lo0)[None], ext(hi0)[None])
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(P(SHARD_AXIS, None),) * 2,
                    out_specs=(P(SHARD_AXIS, None),) * 2)(
                        lo_all, hi_all)
            return compact_step

        # ---- scoring (block-sharded assignment, routed part lookups;
        # chunk stays sharded — no replicated O(V) state here either) ----
        @partial(jax.jit,
                 in_shardings=(self.batch_sharding, self.shard),
                 out_shardings=self.repl)
        def score_step(batch, assign_sh):
            def f(chunk_local, assign_local):
                chunk = chunk_local[0]
                u = chunk[:, 0].astype(jnp.int32)
                v = chunk[:, 1].astype(jnp.int32)
                valid = (u >= 0) & (u < n_) & (v >= 0) & (v < n_) & (u != v)
                au = _lookup(assign_local, jnp.clip(u, 0, n_))
                av = _lookup(assign_local, jnp.clip(v, 0, n_))
                cut = jnp.sum(valid & (au != av), dtype=jnp.int32)
                total = jnp.sum(valid, dtype=jnp.int32)
                return lax.psum(jnp.stack([cut, total])[None], SHARD_AXIS)
            return shard_map(
                f, mesh=mesh,
                in_specs=(P(SHARD_AXIS, None, None), P(SHARD_AXIS)),
                out_specs=P(SHARD_AXIS, None))(batch, assign_sh)[0]

        self.deg_zeros = deg_zeros
        self.deg_step = deg_step
        self.orient_step = orient_step
        self.score_step = score_step
        self.max_rounds = max_rounds
        self._make_compact = _make_compact
        self._compact_cache: dict = {}
        self._make_fold_seg = _make_fold_seg
        self._fold_seg_cache: dict = {}
        self._make_fold_lift = _make_fold_lift
        self._make_fold_lift_hoisted = _make_fold_lift_hoisted
        self._fold_lift_cache: dict = {}

    # compaction floor: the tail's collective bytes are ~ops x D x Q x
    # rounds, and the tail runs hundreds of rounds at the FLOOR width —
    # measured at RMAT-15/D=8, a 4096 floor put ~3.4 GB of the 4 GB
    # per-device total in the tail; 512 cuts that ~8x for a handful of
    # extra (cached, geometrically-sized) compaction programs
    MIN_Q = 1 << 9
    # once the active width compacts to <= TAIL_Q, switch from the
    # lifting program to a jump program with ``self.jumps`` climb steps
    # per round: the remaining work is displacement cascades (one link
    # per jump), and at small Q the extra lookups per round are far
    # cheaper than the rounds they save
    TAIL_Q = 1 << 13

    def _round_cost(self, q: int, jumps: int, lift: bool):
        """(collective ops, bytes received per device) for ONE fixpoint
        round at active width Q: _scatter_min = 2 all_gather +
        2 all_to_all at Q; a jump round adds (jumps-1) lookup pairs at
        Q; a lift round adds ``lift_levels`` lookup pairs at Q plus the
        NON-hoisted squaring pairs at the owned-rows width B (the
        ``hoist_levels`` hoisted squarings are paid once per SEGMENT —
        :func:`_segment_cost`). Every collective ships (D, width) int32
        — the D*Q-words trade documented in the module docstring, now
        *measured* per chunk (diagnostics) instead of only documented."""
        d = self.n_devices
        if lift:
            L, K = self.lift_levels, self.hoist_levels
            ops = 4 + 2 * L + 2 * (L - 1 - K)
            words = d * (4 * q + 2 * L * q + 2 * (L - 1 - K) * self.B)
        else:
            ops = 4 + 2 * (jumps - 1)
            words = d * ops * q
        return ops, 4 * words

    def _segment_cost(self, lift: bool):
        """(ops, bytes/device) paid once per fold CALL: the hoisted
        lifting stack is built per segment, 2 routed collectives per
        hoisted level at width B."""
        if not lift or not self.hoist_levels:
            return 0, 0
        K = self.hoist_levels
        return 2 * K, 4 * self.n_devices * 2 * K * self.B

    def build_step(self, P_sh, pos_sh, batch_dev, stats=None):
        """Fold one sharded batch into the distributed forest via
        host-bounded segments. Returns (P_sh, total_rounds) — identical
        to running the whole fixpoint in one execution, but no single
        device call exceeds ``segment_rounds`` rounds, and the active
        buffers compact (with in-shard dedup) to the pmax live width as
        the set collapses (every routed collective ships D*Q words, so
        smaller Q = proportionally less ICI/DCN traffic per tail round).

        ``stats``: accumulates collective_ops / collective_bytes /
        compactions / q_rounds (sum of Q over rounds) for the run
        diagnostics, plus the cross-backend O(Δ) cost triple
        (host_syncs / device_rounds / folded_bytes — the counters the
        update-vs-rebuild gate compares)."""
        if stats is None:
            stats = {}
        lo_a, hi_a = self.orient_step(pos_sh, batch_dev)
        size = int(lo_a.shape[-1])
        # orient: 2 routed lookups (u, v) at chunk width
        stats["collective_ops"] = stats.get("collective_ops", 0) + 4
        stats["collective_bytes"] = stats.get("collective_bytes", 0) \
            + 4 * 4 * self.n_devices * size
        stats["folded_bytes"] = stats.get("folded_bytes", 0) \
            + int(batch_dev.size) * 4
        total = 0
        # SHEEP_SANITIZE: stray-sync traps around the routed fold loop
        # (the designed pulls below are the only host reads)
        with sanitize.guard("bigv-fold"):
            while True:
                # bulk: stream-descent lifting (few rounds, +V squaring
                # words/round); tail: many-jump rounds (no V-term at all)
                lift = size > self.TAIL_Q
                if lift:
                    key = (self.lift_levels, self.hoist_levels)
                    fold = self._fold_lift_cache.get(key)
                    if fold is None:
                        fold = self._fold_lift_cache[key] = \
                            self._make_fold_lift_hoisted(
                                self.lift_levels, self.hoist_levels) \
                            if self.hoist_levels else \
                            self._make_fold_lift(self.lift_levels)
                    jumps = 0
                else:
                    jumps = self.jumps
                    fold = self._fold_seg_cache.get(jumps)
                    if fold is None:
                        fold = self._fold_seg_cache[jumps] = \
                            self._make_fold_seg(jumps)
                P_sh, lo_a, hi_a, live, r, max_live = fold(P_sh, lo_a, hi_a)
                # the designed per-segment replicated pull of this driver
                with sanitize.sync_ok("bigv-segment-pull"):
                    r = int(r)  # sheeplint: sync-ok
                    live_i = int(live)  # sheeplint: sync-ok
                    ml = int(max_live)  # sheeplint: sync-ok
                total += r
                stats["host_syncs"] = stats.get("host_syncs", 0) + 1
                stats["device_rounds"] = \
                    stats.get("device_rounds", 0) + r
                ops, byts = self._round_cost(size, jumps, lift)
                seg_ops, seg_bytes = self._segment_cost(lift)
                stats["collective_ops"] += ops * r + seg_ops
                stats["collective_bytes"] += byts * r + seg_bytes
                stats["q_rounds"] = stats.get("q_rounds", 0) + size * r
                if live_i == 0 or total >= self.max_rounds:
                    return P_sh, total
                if size > self.MIN_Q and ml <= size // 2:
                    new_size = pow2_at_least(2 * ml, floor=self.MIN_Q)
                    if new_size < size:
                        fn = self._compact_cache.get(new_size)
                        if fn is None:
                            fn = self._compact_cache[new_size] = \
                                self._make_compact(new_size)
                        lo_a, hi_a = fn(lo_a, hi_a)
                        size = new_size
                        stats["compactions"] = stats.get("compactions", 0) + 1

    # ---- host-side helpers ----------------------------------------------
    def _put(self, sharding, arr):
        """Single process: plain device_put. Multi-host: every process
        passes its process-local rows and JAX assembles the global
        array. A batch already materialized on device (device-stream
        synthesis, single-process — see ``run``'s ingest) relays
        without a host crossing."""
        if isinstance(arr, jax.Array):
            return jax.device_put(arr, sharding)
        if self.procs == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(sharding, arr)

    def _local_span(self):
        """This process's row span of a (rows,) block-sharded table."""
        w = self.n_local * self.B
        return self.proc * w, (self.proc + 1) * w

    def _local_block(self, arr) -> np.ndarray:
        """Host copy of this process's rows of a (rows,) sharded array."""
        if self.procs == 1:
            return np.asarray(arr)
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards])

    def _allgather_table(self, local: np.ndarray) -> np.ndarray:
        """Assemble the full (rows,) host table from per-process local
        blocks (one DCN allgather; identical result on every process)."""
        if self.procs == 1:
            return local
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(local)).reshape(-1)

    def _shard_table(self, host_table: np.ndarray):
        """Pad an int32[n+1] host table to (rows,) with the sentinel and
        place it block-sharded (every process holds the full host table;
        each contributes its local span)."""
        padded = np.full(self.rows, self.n, np.int32)
        padded[: self.n + 1] = host_table
        a, b = self._local_span()
        return self._put(self.shard,
                         padded if self.procs == 1 else padded[a:b])

    def run(self, stream, k: int, alpha: float = 1.0,
            weights: Optional[str] = "unit", comm_volume: bool = False,
            timings: Optional[dict] = None, checkpointer=None,
            resume: bool = False):
        """Full vertex-sharded partition run.

        Checkpoint state is the per-process LOCAL block (deg_local —
        int32 when the stream's edge bound proves no overflow, int64
        otherwise; ptable_local int32 — O(V/P) per process, the bigv
        scaling story carried through to recovery); the cadence/
        fingerprint/reconcile machinery is shared with the other
        backends (utils/checkpoint)."""
        from sheep_tpu.core import pure
        from sheep_tpu.ops import score as score_ops
        from sheep_tpu.ops.split import tree_split_host
        from sheep_tpu.parallel.pipeline import (iter_batches_lockstep,
                                                 use_byte_range)
        from sheep_tpu.utils import checkpoint as ckpt
        from sheep_tpu.utils import retry as retry_mod
        from sheep_tpu.utils import watchdog as wd_mod
        from sheep_tpu.utils.fault import maybe_fail
        from sheep_tpu.utils.prefetch import prefetch

        t = timings if timings is not None else {}
        n, cs, d = self.n, self.cs, self.n_devices

        # fault tolerance (ISSUE 9): bounded per-batch retry, single
        # process only (a one-rank retry would desynchronize the
        # collective schedules; multi-host keeps the checkpoint/
        # kill+resume contract plus the stall watchdog). Sound because
        # no bigv program donates its inputs: the pre-batch tables are
        # intact after any fault, so re-folding the same batch is the
        # identical computation.
        policy = retry_mod.RetryPolicy()
        # the per-chunk build point sits OUTSIDE _guarded (legacy kill
        # semantics), so it must not offer kinds it cannot absorb —
        # recoverable oom injection rides the "dispatch" point INSIDE
        # the guarded step instead
        bkinds = ("kill", "stall")
        okinds = ("oom",) if self.procs == 1 else ()

        def _guarded(fn, where, stats=None):
            if self.procs > 1:
                return fn()
            before = sum(policy.attempts.values())
            out = policy.run(fn, where=where)
            grew = sum(policy.attempts.values()) - before
            if grew and stats is not None:
                stats["dispatch_retries"] = \
                    stats.get("dispatch_retries", 0) + grew
            return out

        def batches(start_chunk=0, src=None):
            # device-stream ingest (ISSUE 12): a counter-hash input
            # (the bigv soak generator class) synthesizes every
            # (rows, C, 2) batch directly in device memory — zero host
            # bytes per chunk; _put relays the pre-placed global array.
            # Pass-through, not prefetch: a worker queue of global
            # device batches would hold unmodeled HBM, and there is no
            # host I/O to overlap. Multi-host keeps the host lockstep
            # path (per-process assembly takes host rows). ``src``
            # substitutes the streamed source (the anchored degrees
            # pass streams the delta log's base segment only).
            src = stream if src is None else src
            if self.procs == 1 and is_device_stream(src):
                from sheep_tpu.parallel.pipeline import (
                    _PassThrough, device_lockstep_batches)

                return _PassThrough(device_lockstep_batches(
                    src, cs, self.n_local, n, self.batch_sharding,
                    start_chunk=start_chunk, stats=build_stats))
            return prefetch(iter_batches_lockstep(
                src, cs, self.n_local, n, self.proc, self.procs,
                start_chunk=start_chunk,
                byte_range=use_byte_range(src, self.procs)))

        # state_format "bigv-pos": the checkpointed table block is now
        # POSITION-indexed; the format bump makes --resume against a
        # checkpoint written by the old vertex-indexed layout raise a
        # fingerprint mismatch (collectively, in multi-host) instead of
        # resuming into silently-wrong state; runs without --resume
        # start fresh as always
        meta = ckpt.stream_meta(stream, k, cs, weights=weights, alpha=alpha,
                                comm_volume=comm_volume,
                                state_format="bigv-pos",
                                devices=d, procs=self.procs,
                                text_byte_range=use_byte_range(
                                    stream, self.procs))
        state = ckpt.resume_state(checkpointer, meta, resume,
                                  raise_on_mismatch=self.procs == 1)
        if self.procs > 1 and checkpointer is not None and resume:
            state = ckpt.reconcile_multihost_resume(checkpointer, state, meta)
        from_phase = ckpt.phase_index(state.phase) if state else 0

        root_sp = obs.begin("partition", backend="tpu-bigv", k=int(k),
                            n=int(n), devices=int(d))
        stats_acc = obs.stats_accumulator()
        m_cheap = stream.num_edges_cheap
        obs.progress(backend="tpu-bigv", k=int(k), edges_total=m_cheap)

        # ONE build-stats record across the streaming passes so the
        # ingest counters (device_stream_chunks, ISSUE 12) accumulate
        # wherever batches are synthesized
        build_stats: dict = {}
        # out-of-core residency plane (ISSUE 20): under an explicit
        # SHEEP_CACHE_BYTES budget, build-pass device batches (keyed by
        # absolute chunk index) serve the score pass and the in-process
        # dispatch retries from HBM instead of re-uploading, with
        # checkpoint boundaries as eviction points. Single-process host
        # streams only — device-synth batches have no upload to save,
        # and multi-host residency would skew the collective lockstep.
        rm = None
        if self.procs == 1 and not is_device_stream(stream):
            from sheep_tpu.utils.residency import manager_from_env
            rm = manager_from_env(stats=build_stats)
        # anchored-order inputs (delta: logs, ISSUE 19): degrees stream
        # the BASE segment only (the anchor), build/score the full
        # surviving multiset — same anchored-order semantics as the
        # single-device backends, same unique fixpoint
        anchored = bool(getattr(stream, "order_anchor", False))
        deg_src = stream.anchor_stream() if anchored else None
        # pass 1: degrees (block-sharded int32 accumulator + host fold of
        # the LOCAL block, int32 when the edge bound proves no overflow;
        # resets are jitted on-device zeros, no
        # host zero uploads; one final allgather assembles the table)
        t0 = time.perf_counter()
        sp = obs.begin("degrees+sort")
        obs.progress(phase="degrees", chunks_done=0, edges_done=0)
        flush_every = max(1, (2**31 - 1) // max(2 * cs * d, 1))
        if state:
            deg_local = state.arrays["deg_local"].copy()
        else:
            # int32 host accumulator when the stream's edge bound proves
            # no vertex can see 2^31 endpoints — at the RMAT-30 class the
            # int64 table alone is 8 GB/process; resume keeps the saved
            # dtype so checkpoints stay self-consistent
            ub = stream.num_edges_upper_bound
            deg_dtype = np.int64 if ub is None or 2 * ub >= 2**31 \
                else np.int32
            deg_local = np.zeros(self.n_local * self.B, dtype=deg_dtype)
        if from_phase == 0:
            start = state.chunk_idx if state else 0
            deg_sh = self.deg_zeros()
            since = nb = 0
            # with-exit = deterministic prefetch-worker cancel on
            # exception unwind (utils/prefetch.py close contract)
            with wd_mod.watched(self.procs, "bigv-degrees",
                                self.proc) as wd, \
                    batches(start, src=deg_src) as pf:
                for batch in pf:
                    deg_sh = self.deg_step(deg_sh, self._put(
                        self.batch_sharding, batch))
                    since += 1
                    nb += 1
                    wd.touch(f"degrees batch {nb}")
                    maybe_fail("degrees", nb, kinds=("kill", "stall"))
                    obs.chunk_progress(nb * d, cs, m_cheap)
                    at_ckpt = (checkpointer is not None and
                               checkpointer.due_span((nb - 1) * d, nb * d))
                    if since >= flush_every or at_ckpt:
                        deg_local += self._local_block(deg_sh).astype(
                            deg_local.dtype)
                        deg_sh = self.deg_zeros()
                        since = 0
                    if at_ckpt:
                        checkpointer.save("degrees", start + nb * d,
                                          {"deg_local": deg_local}, meta)
            deg_local += self._local_block(deg_sh).astype(deg_local.dtype)
            deg_sh = None  # free the block-sharded device accumulator
        deg_host = self._allgather_table(deg_local)[:n]

        # host-side elimination order: one stable argsort over degrees;
        # hosts hold hundreds of GB, and the sort is once per run. Only
        # pos is pushed to devices — position space needs no order table
        # there. Everything host-side is int32 (n < 2^31 is enforced at
        # backend entry): at V=2^30 the old int64 pos/order pair alone
        # was 17 GB.
        pos_np = pure.elimination_order(deg_host, dtype=np.int32)
        order_np = np.full(n + 1, n, dtype=np.int32)
        order_np[pos_np] = np.arange(n, dtype=np.int32)
        pos_pad = np.empty(n + 1, dtype=np.int32)
        pos_pad[:n] = pos_np
        pos_pad[n] = n
        pos_sh = self._shard_table(pos_pad)
        del pos_pad
        t["degrees+sort"] = time.perf_counter() - t0
        sp.end()

        # pass 2: the single distributed forest (position-indexed table)
        t0 = time.perf_counter()
        sp = obs.begin("build")
        obs.progress(phase="build", chunks_done=0, edges_done=0)
        total_rounds = 0
        if state and from_phase >= 2:
            P_sh = self._put(self.shard, state.arrays["ptable_local"])
        else:
            if state and state.phase == "build":
                P_sh = self._put(self.shard, state.arrays["ptable_local"])
                start = state.chunk_idx
            else:
                P_sh = self._shard_table(np.full(n + 1, n, np.int32))
                start = 0
            nb = 0
            with wd_mod.watched(self.procs, "bigv-build",
                                self.proc) as wd, batches(start) as pf:
                for batch in pf:
                    seg_sp = obs.begin("segment", i=nb)

                    def _step(b=batch, i=nb, key=start + nb * d):
                        maybe_fail("dispatch", i + 1, kinds=okinds)
                        dev = rm.get(key) if rm is not None else None
                        if dev is None:
                            dev = self._put(self.batch_sharding, b)
                            if rm is not None:
                                rm.admit(key, dev, int(b.nbytes))
                        return self.build_step(
                            P_sh, pos_sh, dev, stats=build_stats)

                    try:
                        P_sh, rounds = _guarded(_step, "bigv.build",
                                                stats=build_stats)
                        total_rounds += rounds
                        stats_acc.absorb(build_stats)
                        seg_sp.end(rounds=int(rounds))
                    finally:
                        # idempotent: balances the span when a fault
                        # unwinds mid-batch (recovered runs must still
                        # render a complete tree)
                        seg_sp.end()
                    nb += 1
                    wd.touch(f"build batch {nb}")
                    obs.chunk_progress(nb * d, cs, m_cheap)
                    maybe_fail("build", nb, kinds=bkinds)
                    if checkpointer is not None and \
                            checkpointer.due_span((nb - 1) * d, nb * d):
                        checkpointer.save(
                            "build", start + nb * d,
                            {"deg_local": deg_local,
                             "ptable_local": self._local_block(P_sh)},
                            meta)
                        if rm is not None:
                            # checkpoint boundary = eviction point: a
                            # retry never re-reads behind the confirmed
                            # index
                            rm.boundary(start + nb * d)
        P_host = self._allgather_table(
            self._local_block(P_sh))[: n + 1]
        t["build"] = time.perf_counter() - t0
        stats_acc.absorb(build_stats)
        sp.end(fixpoint_rounds=int(total_rounds))

        # split on host over O(V) state (native C++); position-indexed
        # table -> vertex parent array: parent[v] = order[P[pos[v]]]
        t0 = time.perf_counter()
        sp = obs.begin("split")
        pp = P_host[pos_np]
        parent = np.where(pp < n, order_np[np.minimum(pp, n)], -1)
        # the native split upcasts parent/pos to int64 copies; drop the
        # tables it does not take first so the split-time peak at the
        # RMAT-30 class stays below the old all-int64 path's
        del pp, order_np
        w = deg_host.astype(np.float64) if weights == "degree" else None
        assign_host = tree_split_host(parent, pos_np, k, weights=w,
                                      alpha=alpha)
        assign_np = np.concatenate([assign_host.astype(np.int32),
                                    np.zeros(1, np.int32)])
        assign_sh = self._shard_table(assign_np)
        t["split"] = time.perf_counter() - t0
        sp.end()

        # pass 3: scoring (sharded chunks, routed lookups into the
        # block-sharded assignment, psum counters)
        t0 = time.perf_counter()
        sp = obs.begin("score")
        obs.progress(phase="score", chunks_done=0, edges_done=0)
        cut = total = 0
        cv_chunks = []
        start = 0
        if state and state.phase == "score":
            start = state.chunk_idx
            cut = int(state.arrays["cut"])
            total = int(state.arrays["total"])
            if comm_volume:
                cv_chunks.append(state.arrays["cv_keys"])
        nb = 0
        with wd_mod.watched(self.procs, "bigv-score",
                            self.proc) as wd, batches(start) as pf:
            for batch in pf:
                key = start + nb * d
                dev = rm.get(key) if rm is not None else None
                if dev is None:
                    dev = self._put(self.batch_sharding, batch)
                    if rm is not None:
                        rm.admit(key, dev, int(batch.nbytes))
                # designed per-batch score pull (two scalars)
                c, tt = np.asarray(self.score_step(  # sheeplint: sync-ok
                    dev, assign_sh))
                cut += int(c)
                total += int(tt)
                if comm_volume:
                    score_ops.accumulate_cv_keys(
                        cv_chunks,
                        score_ops.cut_pair_keys_host(batch, assign_np,
                                                     n, k))
                nb += 1
                wd.touch(f"score batch {nb}")
                maybe_fail("score", nb, kinds=("kill", "stall"))
                obs.chunk_progress(nb * d, cs, m_cheap)
                if checkpointer is not None and \
                        checkpointer.due_span((nb - 1) * d, nb * d):
                    cv_chunks = ckpt.save_score_state(
                        checkpointer, start + nb * d, cut, total,
                        cv_chunks,
                        {"deg_local": deg_local,
                         "ptable_local": self._local_block(P_sh)}, meta,
                        comm_volume)
                    if rm is not None:
                        rm.boundary(start + nb * d)
        cv = None
        if comm_volume:
            keys = ckpt.compact_cv_keys(cv_chunks)
            if self.procs > 1:
                # each process saw only its shard's cut edges: union the
                # per-host key sets (padded allgather, then host unique)
                from jax.experimental import multihost_utils

                lens = multihost_utils.process_allgather(
                    np.array([len(keys)], np.int64))
                mx = max(1, int(lens.max()))
                pad = np.full(mx, -1, np.int64)
                pad[:len(keys)] = keys
                allk = multihost_utils.process_allgather(pad)
                keys = np.unique(allk[allk >= 0])
            cv = int(len(keys))
        balance = pure.part_balance(
            assign_host, k, deg_host if weights == "degree" else None)
        t["score"] = time.perf_counter() - t0
        sp.end()
        root_sp.end()
        if checkpointer is not None:
            checkpointer.clear()

        return {
            "assignment": assign_host, "parent": parent.astype(np.int64),
            "pos": pos_np, "degrees": deg_host, "edge_cut": cut,
            "total_edges": total, "balance": balance, "comm_volume": cv,
            "k": k, "fixpoint_rounds": total_rounds,
            "build_stats": build_stats,
        }


# ---------------------------------------------------------------------------
# process-wide compiled-pipeline cache (ISSUE 19)
# ---------------------------------------------------------------------------
# Every BigVPipeline() re-traces and re-compiles the whole routed program
# set (deg/orient/fold/compact/score close over n, B and the shardings),
# a flat multi-second XLA tax per instance regardless of graph size. The
# pipeline is stateless across runs — everything mutable lives in the
# tables threaded through build_step/run, and the only instance dicts
# are the lazy program caches we WANT to share — so instances are safe
# to reuse whenever every constructor input matches. Keyed on the full
# constructor signature plus the mesh's device ids; bounded LRU so a
# long-lived process sweeping many shapes doesn't pin dead programs.

_PIPE_CACHE: "OrderedDict[tuple, BigVPipeline]" = OrderedDict()
_PIPE_CACHE_MAX = 16


def cached_pipeline(n: int, chunk_edges: int, mesh, jumps: int = 128,
                    max_rounds: int = 1 << 20, segment_rounds: int = 16,
                    dedup_compact: bool = True, lift_levels: int = 0,
                    hoist_bytes: Optional[int] = None) -> BigVPipeline:
    """BigVPipeline with its compiled programs reused across backend
    instances (one-shot builds, resident epoch folds, compaction
    rebuilds — all hit the same programs for the same shape)."""
    hb = hoist_bytes if hoist_bytes is not None \
        else int(os.environ.get("SHEEP_BIGV_HOIST_BYTES", "0"))
    key = (n, chunk_edges, tuple(d.id for d in mesh.devices.flat),
           jumps, max_rounds, segment_rounds, dedup_compact,
           lift_levels, hb)
    pipe = _PIPE_CACHE.get(key)
    if pipe is None:
        pipe = BigVPipeline(n, chunk_edges, mesh, jumps=jumps,
                            max_rounds=max_rounds,
                            segment_rounds=segment_rounds,
                            dedup_compact=dedup_compact,
                            lift_levels=lift_levels,
                            hoist_bytes=hoist_bytes)
        _PIPE_CACHE[key] = pipe
        while len(_PIPE_CACHE) > _PIPE_CACHE_MAX:
            _PIPE_CACHE.popitem(last=False)
    else:
        _PIPE_CACHE.move_to_end(key)
    return pipe
