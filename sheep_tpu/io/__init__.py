from sheep_tpu.io.edgestream import EdgeStream  # noqa: F401
from sheep_tpu.io import formats, generators  # noqa: F401
