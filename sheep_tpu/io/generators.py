"""Graph generators for tests, golden fixtures and scale benchmarks.

SURVEY.md §4: the Zachary karate club is the first driver eval config and
the golden-test fixture; RMAT is both eval config 5 (scale-30 synthetic)
and the soak-test generator. All generators are deterministic under a seed.
"""

from __future__ import annotations

from functools import partial

import numpy as np

# Zachary karate club, 34 vertices / 78 undirected edges (0-indexed).
# Standard public edge list (W. W. Zachary, 1977; same set shipped by
# networkx as karate_club_graph).
_KARATE = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31), (1, 2),
    (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30), (2, 3),
    (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32), (3, 7),
    (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16), (6, 16),
    (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]


def karate_club() -> np.ndarray:
    """34 v / 78 e — driver eval config 1 (BASELINE.json)."""
    return np.asarray(_KARATE, dtype=np.int64)


def path_graph(n: int) -> np.ndarray:
    v = np.arange(n - 1, dtype=np.int64)
    return np.stack([v, v + 1], axis=1)


def star_graph(n: int) -> np.ndarray:
    v = np.arange(1, n, dtype=np.int64)
    return np.stack([np.zeros_like(v), v], axis=1)


def grid_graph(rows: int, cols: int) -> np.ndarray:
    idx = np.arange(rows * cols).reshape(rows, cols)
    horiz = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    vert = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    return np.concatenate([horiz, vert]).astype(np.int64)


def random_graph(n: int, m: int, seed: int = 0, self_loops: bool = False) -> np.ndarray:
    """Erdos-Renyi-ish multigraph: m uniform random edges."""
    rng = np.random.default_rng(seed)
    e = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    if not self_loops:
        loops = e[:, 0] == e[:, 1]
        e[loops, 1] = (e[loops, 1] + 1) % n
    return e


def _rmat_batch(scale: int, cnt: int, rng, a: float, b: float, c: float) -> np.ndarray:
    d = 1.0 - a - b - c
    u = np.zeros(cnt, dtype=np.int64)
    v = np.zeros(cnt, dtype=np.int64)
    for bit in range(scale):
        r1 = rng.random(cnt)
        r2 = rng.random(cnt)
        # recursive quadrant choice: u bit then v bit conditioned on it
        ubit = (r1 > (a + b)).astype(np.int64)
        pv = np.where(ubit == 0, b / (a + b), d / (c + d))
        vbit = (r2 < pv).astype(np.int64)
        u |= ubit << bit
        v |= vbit << bit
    return np.stack([u, v], axis=1)


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    batch: int = 1 << 20,
) -> np.ndarray:
    """R-MAT generator (Chakrabarti et al. 2004), Graph500 parameters.

    2**scale vertices, edge_factor * 2**scale edges. Materializes the full
    (m, 2) output — for graphs that do not fit in RAM (e.g. driver eval
    config 5, scale=30) use :func:`rmat_stream` instead.
    """
    m = edge_factor << scale
    rng = np.random.default_rng(seed)
    out = np.empty((m, 2), dtype=np.int64)
    for off in range(0, m, batch):
        cnt = min(batch, m - off)
        out[off : off + cnt] = _rmat_batch(scale, cnt, rng, a, b, c)
    return out


def rmat_stream(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    chunk: int = 1 << 22,
):
    """Yield RMAT edges chunk-by-chunk without materializing the graph."""
    m = edge_factor << scale
    for i, off in enumerate(range(0, m, chunk)):
        cnt = min(chunk, m - off)
        rng = np.random.default_rng(np.random.SeedSequence([seed, i]))
        yield _rmat_batch(scale, cnt, rng, a, b, c)


# ---------------------------------------------------------------------------
# Counter-based R-MAT: one stateless hash per (edge index, level), so any
# edge RANGE is computable independently — on host (numpy) or ON DEVICE
# (jnp), bit-identically. This is what lets the TPU backend materialize
# synthetic chunks in HBM instead of generating on host and paying the
# host->device upload for every chunk (measured 92 s of a 254 s RMAT-22
# run through a degraded tunnel link, tools/out/20260731T010412/), and
# what makes RMAT-30-class synthetic streams (eval config 5) feedable at
# HBM rate rather than host-numpy rate.
#
# The recursive quadrant choice matches :func:`_rmat_batch`: per bit
# level, u's bit is 1 with probability c+d, then v's bit is 1 with
# probability b/(a+b) (u bit 0) or d/(c+d) (u bit 1). Here the two
# uniforms are the 16-bit halves of one 32-bit hash and the thresholds
# are integers, so numpy and jnp agree exactly (uint32 wraparound
# arithmetic only — no floats anywhere).
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _mix32_int(x: int) -> int:
    """murmur3 fmix32 on a Python int (key premixing, host side)."""
    x &= _M32
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & _M32
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & _M32
    x ^= x >> 16
    return x


def _rmat_hash_keys(scale: int, seed: int):
    """Per-level uint32 keys derived from the seed (Python ints)."""
    s = _mix32_int((seed & _M32) ^ 0x9E3779B9)
    return [_mix32_int(s + 0x9E3779B9 * (lvl + 1)) for lvl in range(scale)]


def _rmat_hash_keys2(keys):
    """Second per-level constant (folded with the high counter word
    mid-mix) — ONE definition shared by the numpy body, the native
    dispatch, and the tests, so the premix cannot drift between the
    bit-identical implementations."""
    return [_mix32_int(k ^ 0x7FEB352D) for k in keys]


def _rmat_hash_thresholds(a: float, b: float, c: float):
    """16-bit integer thresholds for the quadrant choice."""
    d = 1.0 - a - b - c
    t_u = min(65535, max(0, round((c + d) * 65536)))       # P(ubit = 1)
    t_v0 = min(65535, max(0, round(b / (a + b) * 65536)))  # P(vbit=1 | u=0)
    t_v1 = min(65535, max(0, round(d / (c + d) * 65536)))  # P(vbit=1 | u=1)
    return t_u, t_v0, t_v1


def _rmat_hash_uv(xp, elo, ehi, keys, thresholds, dtype):
    """Shared numpy/jnp body: map edge-counter words (elo, ehi) to (u, v).

    ``xp`` is the array namespace (numpy or jax.numpy); all arithmetic is
    uint32 with wraparound, so both namespaces produce identical bits.
    """
    t_u, t_v0, t_v1 = (xp.uint32(t) for t in thresholds)
    u = xp.zeros(elo.shape, dtype=xp.uint32)
    v = xp.zeros(elo.shape, dtype=xp.uint32)
    one = xp.uint32(1)
    for bit, (key, key2) in enumerate(zip(keys, _rmat_hash_keys2(keys))):
        # murmur3 fmix32 over (elo ^ key), folded with ehi mid-mix so
        # both counter words reach every output bit
        h = elo ^ xp.uint32(key)
        h = h ^ (h >> xp.uint32(16))
        h = h * xp.uint32(0x85EBCA6B)
        h = h ^ (ehi ^ xp.uint32(key2))
        h = h ^ (h >> xp.uint32(13))
        h = h * xp.uint32(0xC2B2AE35)
        h = h ^ (h >> xp.uint32(16))
        hu = h >> xp.uint32(16)          # 16-bit uniform for u's bit
        hv = h & xp.uint32(0xFFFF)       # 16-bit uniform for v's bit
        ubit = (hu < t_u).astype(xp.uint32)
        t_v = xp.where(ubit == one, t_v1, t_v0)
        vbit = (hv < t_v).astype(xp.uint32)
        u = u | (ubit << xp.uint32(bit))
        v = v | (vbit << xp.uint32(bit))
    return u.astype(dtype), v.astype(dtype)


def rmat_hash_range(
    scale: int,
    start: int,
    count: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
) -> np.ndarray:
    """Edges [start, start+count) of the counter-based R-MAT stream, as a
    (count, 2) int64 array (host twin of the device generator).

    Large ranges take the native C loop when the core is built (~100x
    the numpy path, bit-identical — the soak generator's bottleneck was
    host hashing); small ranges and toolchain-less hosts use numpy."""
    keys = _rmat_hash_keys(scale, seed)
    th = _rmat_hash_thresholds(a, b, c)
    if count >= 4096:
        from sheep_tpu.core import native

        if native.available():
            return native.rmat_hash_range(scale, start, count, keys,
                                          _rmat_hash_keys2(keys), th)
    idx = start + np.arange(count, dtype=np.int64)
    elo = (idx & _M32).astype(np.uint32)
    ehi = (idx >> 32).astype(np.uint32)
    u, v = _rmat_hash_uv(np, elo, ehi, keys, th, np.int64)
    return np.stack([u, v], axis=1)


_DEVICE_CHUNK_FN = None


def _device_chunk_fn():
    """The jitted device-chunk kernel, created once — jax.jit caches on
    the wrapper object, so the wrapper must be a module singleton or
    every chunk would retrace + recompile the scale-deep unrolled hash
    (jax stays a lazy import: this module is numpy-first)."""
    global _DEVICE_CHUNK_FN
    if _DEVICE_CHUNK_FN is None:
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
        def _chunk(start_words, count, pad_to, keys, th, n):
            lo0, hi0 = start_words
            i = jnp.arange(pad_to, dtype=jnp.uint32)
            elo = lo0 + i
            ehi = hi0 + (elo < lo0).astype(jnp.uint32)  # 64-bit carry
            u, v = _rmat_hash_uv(jnp, elo, ehi, list(keys), th,
                                 jnp.int32)
            e = jnp.stack([u, v], axis=1)
            return jnp.where((i < jnp.uint32(count))[:, None], e,
                             jnp.int32(n))

        _DEVICE_CHUNK_FN = _chunk
    return _DEVICE_CHUNK_FN


def rmat_hash_chunk_device(
    scale: int,
    start: int,
    count: int,
    pad_to: int,
    n: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
):
    """Device twin of :func:`rmat_hash_range`: a (pad_to, 2) int32 chunk
    materialized ON DEVICE (rows past ``count`` hold the sentinel vertex
    ``n``). One compile per (scale, count, pad_to, seed/abc) combination
    — ``start`` is a traced pair of uint32 words (the 64-bit edge
    counter split for 32-bit jax), so streaming a graph reuses one
    compiled program for every full chunk."""
    import jax.numpy as jnp

    keys = tuple(_rmat_hash_keys(scale, seed))
    th = _rmat_hash_thresholds(a, b, c)
    start_words = (jnp.uint32(start & _M32), jnp.uint32(start >> 32))
    return _device_chunk_fn()(start_words, count, pad_to, keys, th, n)


class RmatHashStream:
    """An :class:`~sheep_tpu.io.edgestream.EdgeStream`-compatible synthetic
    stream over the counter-based R-MAT (:func:`rmat_hash_range`), with a
    DEVICE fast path: ``device_chunk(idx, cs, n)`` materializes the padded
    chunk directly in accelerator memory (:func:`rmat_hash_chunk_device`),
    bit-identical to the host chunks every other backend reads — so
    cross-backend equality holds while the TPU path skips the
    host->device upload entirely.

    Chunk access is random (any [start, start+count) range hashes
    independently), which also makes checkpoint resume and round-robin
    sharding exact rather than replay-based.
    """

    def __init__(self, scale: int, edge_factor: int = 16, a: float = 0.57,
                 b: float = 0.19, c: float = 0.19, seed: int = 0):
        if not (1 <= scale <= 32):
            # vertex bits accumulate in uint32 (shifts past bit 31 would
            # silently drop); the device path is further gated to < 2^31
            # ids by check_tpu_vertex_range at backend entry
            raise ValueError(f"rmat-hash scale must be 1..32, got {scale}")
        self.scale = int(scale)
        self.edge_factor = int(edge_factor)
        self.abc = (float(a), float(b), float(c))
        self.seed = int(seed)
        self._m = self.edge_factor << self.scale
        self._n = 1 << self.scale
        # EdgeStream API surface (checkpoint fingerprinting uses
        # content_fingerprint below; there is no replay factory)
        self._edges = None
        self.path = None
        self.fmt = "generator"

    # -- EdgeStream surface -------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def num_edges(self) -> int:
        return self._m

    @property
    def num_edges_cheap(self):
        return self._m

    @property
    def num_edges_upper_bound(self):
        return self._m

    @property
    def num_vertices(self) -> int:
        return self._n

    def clamp_chunk_edges(self, chunk_edges: int, parts: int = 1,
                          floor: int = 1024) -> int:
        return min(chunk_edges, max(floor, -(-self._m // parts)))

    def chunks(self, chunk_edges: int = 1 << 22, shard: int = 0,
               num_shards: int = 1, start_chunk: int = 0,
               byte_range: bool = False):
        """Host chunks by direct range hashing (no generator replay: chunk
        i is rmat_hash_range(i*cs, cs), so skipping ahead is O(1))."""
        if not (0 <= shard < num_shards):
            raise ValueError(f"bad shard {shard}/{num_shards}")
        cs = int(chunk_edges)
        n_chunks = -(-self._m // cs) if self._m else 0
        for i in range(start_chunk, n_chunks):
            if (i % num_shards) == shard:
                yield rmat_hash_range(self.scale, i * cs,
                                      min(cs, self._m - i * cs),
                                      *self.abc, seed=self.seed)

    def count_edges_in_span(self, shard: int, num_shards: int) -> int:
        """O(1) arithmetic (EdgeStream replays the generator to count;
        here chunk ownership is round-robin over fixed-size chunks, so
        the owned-edge total is pure arithmetic — matching what
        summing len(c) over chunks(DEFAULT, shard, num_shards) yields).

        NOTE: like EdgeStream's version, the count assumes
        DEFAULT_CHUNK_EDGES ownership granularity — the method exists
        for the byte-range text path's lockstep accounting and is
        unreachable for path-less streams today; it keeps exact parity
        with the base class's replay semantics."""
        from sheep_tpu.io.edgestream import DEFAULT_CHUNK_EDGES as cs

        n_chunks = -(-self._m // cs)
        owned = len(range(shard, n_chunks, num_shards))
        total = owned * cs
        last = n_chunks - 1
        if n_chunks and (last % num_shards) == shard:
            total -= n_chunks * cs - self._m  # short final chunk
        return total

    def read_all(self) -> np.ndarray:
        return rmat_hash_range(self.scale, 0, self._m, *self.abc,
                               seed=self.seed)

    # -- device fast path ---------------------------------------------------
    def content_fingerprint(self) -> str:
        """Cheap stable identity for checkpoint fingerprints: the
        generator parameters plus a hashed 4096-edge prefix (the full
        first-chunk hash the generic generator fallback would pay costs
        a scale-deep pass over a default-size chunk per partition())."""
        import hashlib

        sample = rmat_hash_range(self.scale, 0, min(4096, self._m),
                                 *self.abc, seed=self.seed)
        tag = (f"rmat_hash/s{self.scale}/ef{self.edge_factor}/"
               f"{self.abc}/{self.seed}/")
        return tag + hashlib.sha1(
            np.ascontiguousarray(sample).tobytes()).hexdigest()

    def device_chunk(self, idx: int, chunk_edges: int, n: int):
        """Padded (chunk_edges, 2) int32 device chunk for global chunk
        ``idx`` — the TPU backend substitutes this for host pad+upload."""
        cs = int(chunk_edges)
        start = idx * cs
        count = max(0, min(cs, self._m - start))
        return rmat_hash_chunk_device(self.scale, start, count, cs, n,
                                      *self.abc, seed=self.seed)

    def num_device_chunks(self, chunk_edges: int) -> int:
        return -(-self._m // int(chunk_edges))
