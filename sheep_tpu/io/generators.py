"""Graph generators for tests, golden fixtures and scale benchmarks.

SURVEY.md §4: the Zachary karate club is the first driver eval config and
the golden-test fixture; RMAT is both eval config 5 (scale-30 synthetic)
and the soak-test generator. All generators are deterministic under a seed.
"""

from __future__ import annotations

import numpy as np

# Zachary karate club, 34 vertices / 78 undirected edges (0-indexed).
# Standard public edge list (W. W. Zachary, 1977; same set shipped by
# networkx as karate_club_graph).
_KARATE = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31), (1, 2),
    (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30), (2, 3),
    (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32), (3, 7),
    (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16), (6, 16),
    (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]


def karate_club() -> np.ndarray:
    """34 v / 78 e — driver eval config 1 (BASELINE.json)."""
    return np.asarray(_KARATE, dtype=np.int64)


def path_graph(n: int) -> np.ndarray:
    v = np.arange(n - 1, dtype=np.int64)
    return np.stack([v, v + 1], axis=1)


def star_graph(n: int) -> np.ndarray:
    v = np.arange(1, n, dtype=np.int64)
    return np.stack([np.zeros_like(v), v], axis=1)


def grid_graph(rows: int, cols: int) -> np.ndarray:
    idx = np.arange(rows * cols).reshape(rows, cols)
    horiz = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    vert = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    return np.concatenate([horiz, vert]).astype(np.int64)


def random_graph(n: int, m: int, seed: int = 0, self_loops: bool = False) -> np.ndarray:
    """Erdos-Renyi-ish multigraph: m uniform random edges."""
    rng = np.random.default_rng(seed)
    e = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    if not self_loops:
        loops = e[:, 0] == e[:, 1]
        e[loops, 1] = (e[loops, 1] + 1) % n
    return e


def _rmat_batch(scale: int, cnt: int, rng, a: float, b: float, c: float) -> np.ndarray:
    d = 1.0 - a - b - c
    u = np.zeros(cnt, dtype=np.int64)
    v = np.zeros(cnt, dtype=np.int64)
    for bit in range(scale):
        r1 = rng.random(cnt)
        r2 = rng.random(cnt)
        # recursive quadrant choice: u bit then v bit conditioned on it
        ubit = (r1 > (a + b)).astype(np.int64)
        pv = np.where(ubit == 0, b / (a + b), d / (c + d))
        vbit = (r2 < pv).astype(np.int64)
        u |= ubit << bit
        v |= vbit << bit
    return np.stack([u, v], axis=1)


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    batch: int = 1 << 20,
) -> np.ndarray:
    """R-MAT generator (Chakrabarti et al. 2004), Graph500 parameters.

    2**scale vertices, edge_factor * 2**scale edges. Materializes the full
    (m, 2) output — for graphs that do not fit in RAM (e.g. driver eval
    config 5, scale=30) use :func:`rmat_stream` instead.
    """
    m = edge_factor << scale
    rng = np.random.default_rng(seed)
    out = np.empty((m, 2), dtype=np.int64)
    for off in range(0, m, batch):
        cnt = min(batch, m - off)
        out[off : off + cnt] = _rmat_batch(scale, cnt, rng, a, b, c)
    return out


def rmat_stream(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    chunk: int = 1 << 22,
):
    """Yield RMAT edges chunk-by-chunk without materializing the graph."""
    m = edge_factor << scale
    for i, off in enumerate(range(0, m, chunk)):
        cnt = min(chunk, m - off)
        rng = np.random.default_rng(np.random.SeedSequence([seed, i]))
        yield _rmat_batch(scale, cnt, rng, a, b, c)
