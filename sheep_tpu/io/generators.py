"""Graph generators for tests, golden fixtures and scale benchmarks.

SURVEY.md §4: the Zachary karate club is the first driver eval config and
the golden-test fixture; RMAT is both eval config 5 (scale-30 synthetic)
and the soak-test generator. All generators are deterministic under a seed.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from sheep_tpu.io.devicestream import DeviceStream

# Zachary karate club, 34 vertices / 78 undirected edges (0-indexed).
# Standard public edge list (W. W. Zachary, 1977; same set shipped by
# networkx as karate_club_graph).
_KARATE = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31), (1, 2),
    (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30), (2, 3),
    (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32), (3, 7),
    (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16), (6, 16),
    (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]


def karate_club() -> np.ndarray:
    """34 v / 78 e — driver eval config 1 (BASELINE.json)."""
    return np.asarray(_KARATE, dtype=np.int64)


def path_graph(n: int) -> np.ndarray:
    v = np.arange(n - 1, dtype=np.int64)
    return np.stack([v, v + 1], axis=1)


def star_graph(n: int) -> np.ndarray:
    v = np.arange(1, n, dtype=np.int64)
    return np.stack([np.zeros_like(v), v], axis=1)


def grid_graph(rows: int, cols: int) -> np.ndarray:
    idx = np.arange(rows * cols).reshape(rows, cols)
    horiz = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    vert = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    return np.concatenate([horiz, vert]).astype(np.int64)


def random_graph(n: int, m: int, seed: int = 0, self_loops: bool = False) -> np.ndarray:
    """Erdos-Renyi-ish multigraph: m uniform random edges."""
    rng = np.random.default_rng(seed)
    e = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    if not self_loops:
        loops = e[:, 0] == e[:, 1]
        e[loops, 1] = (e[loops, 1] + 1) % n
    return e


def _rmat_batch(scale: int, cnt: int, rng, a: float, b: float, c: float) -> np.ndarray:
    d = 1.0 - a - b - c
    u = np.zeros(cnt, dtype=np.int64)
    v = np.zeros(cnt, dtype=np.int64)
    for bit in range(scale):
        r1 = rng.random(cnt)
        r2 = rng.random(cnt)
        # recursive quadrant choice: u bit then v bit conditioned on it
        ubit = (r1 > (a + b)).astype(np.int64)
        pv = np.where(ubit == 0, b / (a + b), d / (c + d))
        vbit = (r2 < pv).astype(np.int64)
        u |= ubit << bit
        v |= vbit << bit
    return np.stack([u, v], axis=1)


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    batch: int = 1 << 20,
) -> np.ndarray:
    """R-MAT generator (Chakrabarti et al. 2004), Graph500 parameters.

    2**scale vertices, edge_factor * 2**scale edges. Materializes the full
    (m, 2) output — for graphs that do not fit in RAM (e.g. driver eval
    config 5, scale=30) use :func:`rmat_stream` instead.
    """
    m = edge_factor << scale
    rng = np.random.default_rng(seed)
    out = np.empty((m, 2), dtype=np.int64)
    for off in range(0, m, batch):
        cnt = min(batch, m - off)
        out[off : off + cnt] = _rmat_batch(scale, cnt, rng, a, b, c)
    return out


def rmat_stream(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    chunk: int = 1 << 22,
):
    """Yield RMAT edges chunk-by-chunk without materializing the graph."""
    m = edge_factor << scale
    for i, off in enumerate(range(0, m, chunk)):
        cnt = min(chunk, m - off)
        rng = np.random.default_rng(np.random.SeedSequence([seed, i]))
        yield _rmat_batch(scale, cnt, rng, a, b, c)


# ---------------------------------------------------------------------------
# Counter-based R-MAT: one stateless hash per (edge index, level), so any
# edge RANGE is computable independently — on host (numpy) or ON DEVICE
# (jnp), bit-identically. This is what lets the TPU backend materialize
# synthetic chunks in HBM instead of generating on host and paying the
# host->device upload for every chunk (measured 92 s of a 254 s RMAT-22
# run through a degraded tunnel link, tools/out/20260731T010412/), and
# what makes RMAT-30-class synthetic streams (eval config 5) feedable at
# HBM rate rather than host-numpy rate.
#
# The recursive quadrant choice matches :func:`_rmat_batch`: per bit
# level, u's bit is 1 with probability c+d, then v's bit is 1 with
# probability b/(a+b) (u bit 0) or d/(c+d) (u bit 1). Here the two
# uniforms are the 16-bit halves of one 32-bit hash and the thresholds
# are integers, so numpy and jnp agree exactly (uint32 wraparound
# arithmetic only — no floats anywhere).
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _mix32_int(x: int) -> int:
    """murmur3 fmix32 on a Python int (key premixing, host side)."""
    x &= _M32
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & _M32
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & _M32
    x ^= x >> 16
    return x


def _rmat_hash_keys(scale: int, seed: int):
    """Per-level uint32 keys derived from the seed (Python ints)."""
    s = _mix32_int((seed & _M32) ^ 0x9E3779B9)
    return [_mix32_int(s + 0x9E3779B9 * (lvl + 1)) for lvl in range(scale)]


def _rmat_hash_keys2(keys):
    """Second per-level constant (folded with the high counter word
    mid-mix) — ONE definition shared by the numpy body, the native
    dispatch, and the tests, so the premix cannot drift between the
    bit-identical implementations."""
    return [_mix32_int(k ^ 0x7FEB352D) for k in keys]


def _rmat_hash_thresholds(a: float, b: float, c: float):
    """16-bit integer thresholds for the quadrant choice."""
    d = 1.0 - a - b - c
    t_u = min(65535, max(0, round((c + d) * 65536)))       # P(ubit = 1)
    t_v0 = min(65535, max(0, round(b / (a + b) * 65536)))  # P(vbit=1 | u=0)
    t_v1 = min(65535, max(0, round(d / (c + d) * 65536)))  # P(vbit=1 | u=1)
    return t_u, t_v0, t_v1


def _rmat_hash_uv(xp, elo, ehi, keys, thresholds, dtype):
    """Shared numpy/jnp body: map edge-counter words (elo, ehi) to (u, v).

    ``xp`` is the array namespace (numpy or jax.numpy); all arithmetic is
    uint32 with wraparound, so both namespaces produce identical bits.
    """
    t_u, t_v0, t_v1 = (xp.uint32(t) for t in thresholds)
    u = xp.zeros(elo.shape, dtype=xp.uint32)
    v = xp.zeros(elo.shape, dtype=xp.uint32)
    one = xp.uint32(1)
    for bit, (key, key2) in enumerate(zip(keys, _rmat_hash_keys2(keys))):
        # murmur3 fmix32 over (elo ^ key), folded with ehi mid-mix so
        # both counter words reach every output bit
        h = elo ^ xp.uint32(key)
        h = h ^ (h >> xp.uint32(16))
        h = h * xp.uint32(0x85EBCA6B)
        h = h ^ (ehi ^ xp.uint32(key2))
        h = h ^ (h >> xp.uint32(13))
        h = h * xp.uint32(0xC2B2AE35)
        h = h ^ (h >> xp.uint32(16))
        hu = h >> xp.uint32(16)          # 16-bit uniform for u's bit
        hv = h & xp.uint32(0xFFFF)       # 16-bit uniform for v's bit
        ubit = (hu < t_u).astype(xp.uint32)
        t_v = xp.where(ubit == one, t_v1, t_v0)
        vbit = (hv < t_v).astype(xp.uint32)
        u = u | (ubit << xp.uint32(bit))
        v = v | (vbit << xp.uint32(bit))
    return u.astype(dtype), v.astype(dtype)


def rmat_hash_range(
    scale: int,
    start: int,
    count: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
) -> np.ndarray:
    """Edges [start, start+count) of the counter-based R-MAT stream, as a
    (count, 2) int64 array (host twin of the device generator).

    Large ranges take the native C loop when the core is built (~100x
    the numpy path, bit-identical — the soak generator's bottleneck was
    host hashing); small ranges and toolchain-less hosts use numpy."""
    keys = _rmat_hash_keys(scale, seed)
    th = _rmat_hash_thresholds(a, b, c)
    if count >= 4096:
        from sheep_tpu.core import native

        if native.available():
            return native.rmat_hash_range(scale, start, count, keys,
                                          _rmat_hash_keys2(keys), th)
    idx = start + np.arange(count, dtype=np.int64)
    elo = (idx & _M32).astype(np.uint32)
    ehi = (idx >> 32).astype(np.uint32)
    u, v = _rmat_hash_uv(np, elo, ehi, keys, th, np.int64)
    return np.stack([u, v], axis=1)


_DEVICE_CHUNK_FN = None


def _device_chunk_fn():
    """The jitted device-chunk kernel, created once — jax.jit caches on
    the wrapper object, so the wrapper must be a module singleton or
    every chunk would retrace + recompile the scale-deep unrolled hash
    (jax stays a lazy import: this module is numpy-first)."""
    global _DEVICE_CHUNK_FN
    if _DEVICE_CHUNK_FN is None:
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
        def _chunk(start_words, count, pad_to, keys, th, n):
            lo0, hi0 = start_words
            i = jnp.arange(pad_to, dtype=jnp.uint32)
            elo = lo0 + i
            ehi = hi0 + (elo < lo0).astype(jnp.uint32)  # 64-bit carry
            u, v = _rmat_hash_uv(jnp, elo, ehi, list(keys), th,
                                 jnp.int32)
            e = jnp.stack([u, v], axis=1)
            return jnp.where((i < jnp.uint32(count))[:, None], e,
                             jnp.int32(n))

        _DEVICE_CHUNK_FN = _chunk
    return _DEVICE_CHUNK_FN


def rmat_hash_chunk_device(
    scale: int,
    start: int,
    count: int,
    pad_to: int,
    n: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
):
    """Device twin of :func:`rmat_hash_range`: a (pad_to, 2) int32 chunk
    materialized ON DEVICE (rows past ``count`` hold the sentinel vertex
    ``n``). One compile per (scale, count, pad_to, seed/abc) combination
    — ``start`` is a traced pair of uint32 words (the 64-bit edge
    counter split for 32-bit jax), so streaming a graph reuses one
    compiled program for every full chunk."""
    import jax.numpy as jnp

    keys = tuple(_rmat_hash_keys(scale, seed))
    th = _rmat_hash_thresholds(a, b, c)
    start_words = (jnp.uint32(start & _M32), jnp.uint32(start >> 32))
    return _device_chunk_fn()(start_words, count, pad_to, keys, th, n)


class _CounterHashStream:
    """Shared :class:`~sheep_tpu.io.edgestream.EdgeStream` surface for
    replay-free counter-hash synthetic streams (R-MAT, SBM). Subclasses
    set ``_n``/``_m`` and implement ``_range(start, count)`` (host chunk
    as an int64 (count, 2) array); they may also provide the
    ``device_chunk`` fast path the TPU backend probes for.

    Chunk access is random (any [start, start+count) range hashes
    independently), which also makes checkpoint resume and round-robin
    sharding exact rather than replay-based.
    """

    path = None
    fmt = "generator"

    def _range(self, start: int, count: int) -> np.ndarray:
        raise NotImplementedError

    # -- EdgeStream surface -------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def num_edges(self) -> int:
        return self._m

    @property
    def num_edges_cheap(self):
        return self._m

    @property
    def num_edges_upper_bound(self):
        return self._m

    @property
    def num_vertices(self) -> int:
        return self._n

    def clamp_chunk_edges(self, chunk_edges: int, parts: int = 1,
                          floor: int = 1024) -> int:
        return min(chunk_edges, max(floor, -(-self._m // parts)))

    def chunks(self, chunk_edges: int = 1 << 22, shard: int = 0,
               num_shards: int = 1, start_chunk: int = 0,
               byte_range: bool = False):
        """Host chunks by direct range hashing (no generator replay: chunk
        i is _range(i*cs, cs), so skipping ahead is O(1))."""
        if not (0 <= shard < num_shards):
            raise ValueError(f"bad shard {shard}/{num_shards}")
        cs = int(chunk_edges)
        n_chunks = -(-self._m // cs) if self._m else 0
        for i in range(start_chunk, n_chunks):
            if (i % num_shards) == shard:
                yield self._range(i * cs, min(cs, self._m - i * cs))

    def read_all(self) -> np.ndarray:
        return self._range(0, self._m)

    def num_device_chunks(self, chunk_edges: int) -> int:
        return -(-self._m // int(chunk_edges))

    def count_edges_in_span(self, shard: int, num_shards: int) -> int:
        """O(1) arithmetic (EdgeStream replays the generator to count;
        here chunk ownership is round-robin over fixed-size chunks, so
        the owned-edge total is pure arithmetic — matching what
        summing len(c) over chunks(DEFAULT, shard, num_shards) yields).

        NOTE: like EdgeStream's version, the count assumes
        DEFAULT_CHUNK_EDGES ownership granularity — the method exists
        for the byte-range text path's lockstep accounting and is
        unreachable for path-less streams today; it keeps exact parity
        with the base class's replay semantics."""
        from sheep_tpu.io.edgestream import DEFAULT_CHUNK_EDGES as cs

        n_chunks = -(-self._m // cs)
        owned = len(range(shard, n_chunks, num_shards))
        total = owned * cs
        last = n_chunks - 1
        if n_chunks and (last % num_shards) == shard:
            total -= n_chunks * cs - self._m  # short final chunk
        return total

    def _fingerprint(self, tag: str) -> str:
        """Cheap stable identity for checkpoint fingerprints: the
        generator parameters plus a hashed 4096-edge prefix (the full
        first-chunk hash the generic generator fallback would pay costs
        a scale-deep pass over a default-size chunk per partition())."""
        import hashlib

        sample = self._range(0, min(4096, self._m))
        return tag + hashlib.sha1(
            np.ascontiguousarray(sample).tobytes()).hexdigest()


class RmatHashStream(DeviceStream, _CounterHashStream):
    """Counter-based R-MAT stream (:func:`rmat_hash_range`), a
    :class:`~sheep_tpu.io.devicestream.DeviceStream`:
    ``device_chunk(idx, cs, n)`` materializes the padded chunk directly
    in accelerator memory (:func:`rmat_hash_chunk_device`),
    bit-identical to the host chunks every other backend reads — so
    cross-backend equality holds while the device-recognizing drivers
    skip host generation AND the host->device upload entirely.
    """

    def __init__(self, scale: int, edge_factor: int = 16, a: float = 0.57,
                 b: float = 0.19, c: float = 0.19, seed: int = 0):
        if not (1 <= scale <= 32):
            # vertex bits accumulate in uint32 (shifts past bit 31 would
            # silently drop); the device path is further gated to < 2^31
            # ids by check_tpu_vertex_range at backend entry
            raise ValueError(f"rmat-hash scale must be 1..32, got {scale}")
        self.scale = int(scale)
        self.edge_factor = int(edge_factor)
        self.abc = (float(a), float(b), float(c))
        self.seed = int(seed)
        self._m = self.edge_factor << self.scale
        self._n = 1 << self.scale

    def _range(self, start: int, count: int) -> np.ndarray:
        return rmat_hash_range(self.scale, start, count, *self.abc,
                               seed=self.seed)

    def content_fingerprint(self) -> str:
        return self._fingerprint(f"rmat_hash/s{self.scale}/"
                                 f"ef{self.edge_factor}/{self.abc}/"
                                 f"{self.seed}/")

    # -- device fast path ---------------------------------------------------
    def device_chunk(self, idx: int, chunk_edges: int, n: int):
        """Padded (chunk_edges, 2) int32 device chunk for global chunk
        ``idx`` — the TPU backend substitutes this for host pad+upload."""
        cs = int(chunk_edges)
        start = idx * cs
        count = max(0, min(cs, self._m - start))
        return rmat_hash_chunk_device(self.scale, start, count, cs, n,
                                      *self.abc, seed=self.seed)


# ---------------------------------------------------------------------------
# Counter-based planted partition (SBM): ground-truth community structure
# at arbitrary scale, replay-free like the R-MAT above. The real eval
# graphs with community structure (LiveJournal/twitter/uk) are
# unreachable in this environment, and R-MAT is an expander (cut ratios
# 93-97% are a property of the GRAPH, not the partitioner) — this stream
# is how "low communication volume" (SURVEY.md §1's defining output
# property) gets at-scale evidence: k planted blocks, an exact
# inter-block edge fraction p_out, and a known optimal cut to compare
# the recovered cut against (VERDICT r3 item 5).
#
# Model (per edge counter i, five independent 32-bit uniforms):
#   cross  = h0 < round(p_out * 2^32)
#   bu     = h1 & (n_blocks - 1)              # blocks are power-of-two
#   bv     = distinct-from-bu pick from h2    # only used when cross
#   u      = bu * block_size + (h3 & (block_size - 1))
#   v      = (cross ? bv : bu) * block_size + (h4 & (block_size - 1))
# so a cross edge NEVER lands inside a block: the planted cut fraction
# is exactly the Bernoulli(p_out) rate, and vertex ids are contiguous
# within blocks (ground truth = v >> block_bits). Intra edges may be
# self-loops with probability 2^-block_bits (harmless: never cut).
# ---------------------------------------------------------------------------


def _sbm_hash_keys(seed: int):
    """Five per-field uint32 keys (decide, bu, bv, uoff, voff)."""
    s = _mix32_int((seed & _M32) ^ 0x2545F491)
    return [_mix32_int(s + 0x9E3779B9 * (f + 1)) for f in range(5)]


def _hash_fields(xp, elo, ehi, keys):
    """Per-key independent 32-bit uniforms for one edge-counter word
    pair — the shared field-hash loop of every counter-hash stream
    (murmur3 fmix32 over elo ^ key, folded with ehi mid-mix). All
    uint32 wraparound arithmetic: numpy and jnp agree bit-exactly."""
    fields = []
    for key, key2 in zip(keys, _rmat_hash_keys2(keys)):
        h = elo ^ xp.uint32(key)
        h = h ^ (h >> xp.uint32(16))
        h = h * xp.uint32(0x85EBCA6B)
        h = h ^ (ehi ^ xp.uint32(key2))
        h = h ^ (h >> xp.uint32(13))
        h = h * xp.uint32(0xC2B2AE35)
        h = h ^ (h >> xp.uint32(16))
        fields.append(h)
    return fields


def _sbm_hash_uv(xp, elo, ehi, keys, t_out, n_blocks, block_bits, dtype):
    """Shared numpy/jnp body: edge-counter words -> (u, v). All uint32
    wraparound arithmetic, so host and device bits agree exactly."""
    h_cross, h_bu, h_bv, h_uo, h_vo = _hash_fields(xp, elo, ehi, keys)
    cross = h_cross < xp.uint32(t_out)
    bu = h_bu & xp.uint32(n_blocks - 1)
    # distinct second block: draw from [0, n_blocks-1) and skip past bu
    # (modulo bias <= (n_blocks-1)/2^32 — immaterial for any usable
    # block count)
    bvr = h_bv % xp.uint32(n_blocks - 1)
    bv = bvr + (bvr >= bu).astype(xp.uint32)
    b2 = xp.where(cross, bv, bu)
    off_mask = xp.uint32((1 << block_bits) - 1)
    u = (bu << xp.uint32(block_bits)) | (h_uo & off_mask)
    v = (b2 << xp.uint32(block_bits)) | (h_vo & off_mask)
    return u.astype(dtype), v.astype(dtype)


def _sbm_t_out(p_out: float) -> int:
    """p_out as a uint32 threshold (clamped; p_out=1.0 maps to 2^32-1,
    i.e. 'all cross' short of one edge in 4 billion)."""
    return min(_M32, max(0, round(float(p_out) * 4294967296.0)))


def sbm_hash_range(scale: int, start: int, count: int, n_blocks: int,
                   p_out: float, seed: int = 0) -> np.ndarray:
    """Edges [start, start+count) of the counter-based planted-partition
    stream, as a (count, 2) int64 array (host twin of the device path).

    Large ranges take the native C loop when the core is built
    (bit-identical, ~100x numpy — at-scale quality runs re-stream the
    graph once per refine round); small ranges and toolchain-less hosts
    use numpy."""
    nb = int(n_blocks)
    # mirror SbmHashStream's check: this is a public entry point too
    # (tests/tools call it directly), and nb=1 is a modulo-by-zero in
    # _sbm_hash_uv (SIGFPE in the native path) while a non-power-of-two
    # silently corrupts the block structure via the (nb-1) mask
    if nb < 2 or nb & (nb - 1) or nb > (1 << scale):
        raise ValueError(f"n_blocks must be a power of two in "
                         f"[2, 2**scale], got {n_blocks}")
    keys = _sbm_hash_keys(seed)
    block_bits = scale - (nb.bit_length() - 1)
    if count >= 4096:
        from sheep_tpu.core import native

        if native.available() and native.has_sbm_hash():
            return native.sbm_hash_range(
                start, count, keys, _rmat_hash_keys2(keys),
                _sbm_t_out(p_out), nb, block_bits)
    idx = start + np.arange(count, dtype=np.int64)
    elo = (idx & _M32).astype(np.uint32)
    ehi = (idx >> 32).astype(np.uint32)
    u, v = _sbm_hash_uv(np, elo, ehi, keys, _sbm_t_out(p_out), nb,
                        block_bits, np.int64)
    return np.stack([u, v], axis=1)


_SBM_DEVICE_CHUNK_FN = None


def _sbm_device_chunk_fn():
    """Jitted device-chunk kernel singleton (same rationale as
    :func:`_device_chunk_fn`: jit caches on the wrapper object)."""
    global _SBM_DEVICE_CHUNK_FN
    if _SBM_DEVICE_CHUNK_FN is None:
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7))
        def _chunk(start_words, count, pad_to, keys, t_out, n_blocks,
                   block_bits, n):
            lo0, hi0 = start_words
            i = jnp.arange(pad_to, dtype=jnp.uint32)
            elo = lo0 + i
            ehi = hi0 + (elo < lo0).astype(jnp.uint32)  # 64-bit carry
            u, v = _sbm_hash_uv(jnp, elo, ehi, list(keys), t_out,
                                n_blocks, block_bits, jnp.int32)
            e = jnp.stack([u, v], axis=1)
            return jnp.where((i < jnp.uint32(count))[:, None], e,
                             jnp.int32(n))

        _SBM_DEVICE_CHUNK_FN = _chunk
    return _SBM_DEVICE_CHUNK_FN


class SbmHashStream(DeviceStream, _CounterHashStream):
    """Planted-partition (stochastic block model) counter-hash stream:
    2**scale vertices in ``n_blocks`` equal contiguous blocks, each edge
    inter-block with probability ``p_out``. Ground truth is
    :meth:`ground_truth`; the planted cut ratio is exactly the Bernoulli
    cross rate, so a partitioner that recovers the blocks at
    k = n_blocks scores cut_ratio ~= p_out.

    A :class:`~sheep_tpu.io.devicestream.DeviceStream` like
    :class:`RmatHashStream` (bit-identical host and device chunks).
    """

    def __init__(self, scale: int, n_blocks: int = 64,
                 p_out: float = 0.05, edge_factor: int = 16,
                 seed: int = 0):
        if not (1 <= scale <= 31):
            # ids must fit int32 on-device (no < 2^31 backend gate can
            # widen a generator that emits 2^31 ids)
            raise ValueError(f"sbm-hash scale must be 1..31, got {scale}")
        nb = int(n_blocks)
        if nb < 2 or nb & (nb - 1) or nb > (1 << scale):
            raise ValueError(f"n_blocks must be a power of two in "
                             f"[2, 2**scale], got {n_blocks}")
        if not (0.0 <= p_out <= 1.0):
            raise ValueError(f"p_out must be in [0, 1], got {p_out}")
        self.scale = int(scale)
        self.n_blocks = nb
        self.block_bits = self.scale - (nb.bit_length() - 1)
        self.p_out = float(p_out)
        self.edge_factor = int(edge_factor)
        self.seed = int(seed)
        self._m = self.edge_factor << self.scale
        self._n = 1 << self.scale

    def _range(self, start: int, count: int) -> np.ndarray:
        return sbm_hash_range(self.scale, start, count, self.n_blocks,
                              self.p_out, seed=self.seed)

    def content_fingerprint(self) -> str:
        return self._fingerprint(
            f"sbm_hash/s{self.scale}/b{self.n_blocks}/p{self.p_out}/"
            f"ef{self.edge_factor}/{self.seed}/")

    def ground_truth(self, k: int | None = None) -> np.ndarray:
        """The planted assignment at ``k`` parts (default: one part per
        block). ``n_blocks`` must be divisible by ``k``: consecutive
        blocks group into a part, preserving the planted cut. O(V)
        memory — ground truth is for scoring, not for streaming."""
        k = self.n_blocks if k is None else int(k)
        if k < 1 or self.n_blocks % k:
            raise ValueError(f"k must divide n_blocks={self.n_blocks}, "
                             f"got {k}")
        per = self.n_blocks // k
        blocks = np.arange(self._n, dtype=np.int64) >> self.block_bits
        return (blocks // per).astype(np.int32)

    def planted_cut_ratio(self, k: int | None = None) -> float:
        """The exact expected cut ratio of the planted partition at
        ``k`` parts (default: one part per block, where cross edges are
        inter-block by construction). At a GROUPED ``k`` (n_blocks/k
        consecutive blocks per part — :meth:`ground_truth`'s grouping) a
        cross edge stays intra-part when its distinct second block lands
        in the same group: probability (per - 1)/(n_blocks - 1), so the
        grouped planted ratio is p * (n_blocks - per)/(n_blocks - 1).
        This is the per-level optimum the cut ledger's level-0 row is
        measured against (ISSUE 13)."""
        p = _sbm_t_out(self.p_out) / 4294967296.0
        if k is None or k == self.n_blocks:
            return p
        if k < 1 or self.n_blocks % k:
            raise ValueError(f"k must divide n_blocks={self.n_blocks}, "
                             f"got {k}")
        per = self.n_blocks // k
        return p * (self.n_blocks - per) / max(self.n_blocks - 1, 1)

    # -- device fast path ---------------------------------------------------
    def device_chunk(self, idx: int, chunk_edges: int, n: int):
        cs = int(chunk_edges)
        start = idx * cs
        count = max(0, min(cs, self._m - start))
        return _sbm_device_chunk_fn()(
            (np.uint32(start & _M32), np.uint32(start >> 32)), count, cs,
            tuple(_sbm_hash_keys(self.seed)), _sbm_t_out(self.p_out),
            self.n_blocks, self.block_bits, n)


# ---------------------------------------------------------------------------
# Quality-scenario streams (ISSUE 13): the quality CI gate sweeps graph
# CLASSES, not one generator — bipartite, near-clique and power-law-
# degree community structure each stress a different partitioner
# behavior (2PS picks its strategy from exactly these degree/structure
# signals). All three are counter-hash streams like the SBM above:
# random-access chunks, deterministic under a seed, planted ground
# truth where one exists.
# ---------------------------------------------------------------------------


class NearCliqueStream(SbmHashStream):
    """Planted NEAR-CLIQUE communities: 2**scale vertices in dense
    blocks of ``2**clique_bits`` vertices, each edge intra-clique with
    probability ``1 - p_out``. Structurally this IS the planted
    partition with n_blocks = 2**(scale - clique_bits) — the point is
    the REGIME: with edge_factor around 2**(clique_bits - 1) each block
    approaches clique density (~ef * 2**clique_bits intra edges against
    ~2**(2*clique_bits - 1) pairs), the near-clique scenario the
    quality gate needs (a partitioner that shatters cliques shows up
    immediately in the cut). Reuses the SBM hash body wholesale, so the
    device fast path and ground truth come for free and stay
    bit-identical to the host chunks."""

    def __init__(self, scale: int, clique_bits: int, p_out: float = 0.01,
                 edge_factor: int = 8, seed: int = 0):
        cb = int(clique_bits)
        if not (1 <= cb < int(scale)):
            raise ValueError(f"clique_bits must be in [1, scale), got "
                             f"{clique_bits}")
        super().__init__(scale, 1 << (int(scale) - cb), p_out,
                         edge_factor, seed=seed)
        self.clique_bits = cb

    def content_fingerprint(self) -> str:
        return self._fingerprint(
            f"nearclique_hash/s{self.scale}/c{self.clique_bits}/"
            f"p{self.p_out}/ef{self.edge_factor}/{self.seed}/")


class PowerlawSbmHashStream(_CounterHashStream):
    """Planted partition with POWER-LAW within-block degrees: block
    choice is the SBM's (cross with probability ``p_out``, distinct
    second block), but the within-block vertex offsets come from the
    R-MAT recursive bit walk over ``block_bits`` levels instead of a
    uniform draw — so every block has Graph500-shaped hubs while the
    planted cut stays exactly Bernoulli(p_out). This is the
    "power-law SBM" scenario of the quality gate: LP refinement sees
    hub-dominated majorities where the flat SBM sees uniform ones, and
    a recipe that only works on flat degree distributions fails here
    first (the 2PS observation, inverted)."""

    def __init__(self, scale: int, n_blocks: int = 16,
                 p_out: float = 0.05, edge_factor: int = 16,
                 seed: int = 0,
                 a: float = 0.57, b: float = 0.19, c: float = 0.19):
        if not (1 <= scale <= 31):
            raise ValueError(f"plsbm-hash scale must be 1..31, got {scale}")
        nb = int(n_blocks)
        if nb < 2 or nb & (nb - 1) or nb > (1 << (scale - 1)):
            # nb == 2**scale would leave block_bits == 0 (no offset
            # walk at all); require at least 2 vertices per block
            raise ValueError(f"n_blocks must be a power of two in "
                             f"[2, 2**(scale-1)], got {n_blocks}")
        if not (0.0 <= p_out <= 1.0):
            raise ValueError(f"p_out must be in [0, 1], got {p_out}")
        self.scale = int(scale)
        self.n_blocks = nb
        self.block_bits = self.scale - (nb.bit_length() - 1)
        self.p_out = float(p_out)
        self.edge_factor = int(edge_factor)
        self.seed = int(seed)
        self.abc = (float(a), float(b), float(c))
        self._m = self.edge_factor << self.scale
        self._n = 1 << self.scale

    def _range(self, start: int, count: int) -> np.ndarray:
        idx = start + np.arange(count, dtype=np.int64)
        elo = (idx & _M32).astype(np.uint32)
        ehi = (idx >> 32).astype(np.uint32)
        # block fields: the SBM draw (seed-distinct from the offset keys)
        keys = _sbm_hash_keys(self.seed)
        h_cross, h_bu, h_bv = _hash_fields(np, elo, ehi, keys[:3])
        cross = h_cross < np.uint32(_sbm_t_out(self.p_out))
        bu = h_bu & np.uint32(self.n_blocks - 1)
        bvr = h_bv % np.uint32(self.n_blocks - 1)
        bv = bvr + (bvr >= bu).astype(np.uint32)
        b2 = np.where(cross, bv, bu)
        # within-block offsets: the R-MAT bit walk over block_bits
        # levels (distinct key schedule so offsets decorrelate from the
        # block fields)
        okeys = _rmat_hash_keys(self.block_bits,
                                _mix32_int(self.seed ^ 0x6A09E667))
        th = _rmat_hash_thresholds(*self.abc)
        uo, vo = _rmat_hash_uv(np, elo, ehi, okeys, th, np.uint32)
        u = (bu << np.uint32(self.block_bits)) | uo
        v = (b2 << np.uint32(self.block_bits)) | vo
        return np.stack([u.astype(np.int64), v.astype(np.int64)], axis=1)

    def content_fingerprint(self) -> str:
        return self._fingerprint(
            f"plsbm_hash/s{self.scale}/b{self.n_blocks}/p{self.p_out}/"
            f"ef{self.edge_factor}/{self.abc}/{self.seed}/")

    ground_truth = SbmHashStream.ground_truth
    planted_cut_ratio = SbmHashStream.planted_cut_ratio


class BipartiteHashStream(_CounterHashStream):
    """Planted BIPARTITE communities: 2**scale vertices split into a
    left half [0, n/2) and a right half [n/2, n); every edge crosses
    the halves (no intra-side edges, ever). ``n_blocks`` planted
    bi-communities each own one contiguous left segment and the
    matching right segment; an edge joins its block's two sides with
    probability ``1 - p_out`` and a distinct block's right side
    otherwise — so the planted cut at k = n_blocks is exactly
    Bernoulli(p_out), like the SBM, but every neighborhood is
    one-sided. This is the quality gate's bipartite scenario: degree
    signals that implicitly assume triangles/within-part edges (an LP
    majority over SAME-side neighbors, for one) get zero help here."""

    def __init__(self, scale: int, n_blocks: int = 8,
                 p_out: float = 0.02, edge_factor: int = 16,
                 seed: int = 0):
        if not (2 <= scale <= 31):
            raise ValueError(f"bipartite-hash scale must be 2..31, "
                             f"got {scale}")
        nb = int(n_blocks)
        half = 1 << (int(scale) - 1)
        if nb < 2 or nb & (nb - 1) or nb > half:
            raise ValueError(f"n_blocks must be a power of two in "
                             f"[2, 2**(scale-1)], got {n_blocks}")
        if not (0.0 <= p_out <= 1.0):
            raise ValueError(f"p_out must be in [0, 1], got {p_out}")
        self.scale = int(scale)
        self.n_blocks = nb
        # per-SIDE block span: half / n_blocks vertices
        self.block_bits = (self.scale - 1) - (nb.bit_length() - 1)
        self.p_out = float(p_out)
        self.edge_factor = int(edge_factor)
        self.seed = int(seed)
        self._m = self.edge_factor << self.scale
        self._n = 1 << self.scale

    def _range(self, start: int, count: int) -> np.ndarray:
        idx = start + np.arange(count, dtype=np.int64)
        elo = (idx & _M32).astype(np.uint32)
        ehi = (idx >> 32).astype(np.uint32)
        keys = _sbm_hash_keys(_mix32_int(self.seed ^ 0x3C6EF372))
        h_cross, h_bu, h_bv, h_uo, h_vo = _hash_fields(np, elo, ehi, keys)
        cross = h_cross < np.uint32(_sbm_t_out(self.p_out))
        bu = h_bu & np.uint32(self.n_blocks - 1)
        bvr = h_bv % np.uint32(self.n_blocks - 1)
        bv = bvr + (bvr >= bu).astype(np.uint32)
        b2 = np.where(cross, bv, bu)
        off_mask = np.uint32((1 << self.block_bits) - 1)
        half = np.int64(self._n >> 1)
        u = (bu.astype(np.int64) << self.block_bits) \
            | (h_uo & off_mask).astype(np.int64)
        v = half + ((b2.astype(np.int64) << self.block_bits)
                    | (h_vo & off_mask).astype(np.int64))
        return np.stack([u, v], axis=1)

    def content_fingerprint(self) -> str:
        return self._fingerprint(
            f"bipartite_hash/s{self.scale}/b{self.n_blocks}/"
            f"p{self.p_out}/ef{self.edge_factor}/{self.seed}/")

    def ground_truth(self, k: int | None = None) -> np.ndarray:
        """Planted assignment at ``k`` parts (default: one per
        bi-community). Each part takes a block's left AND right
        segments, so the planted partition never cuts the half
        boundary structure itself."""
        k = self.n_blocks if k is None else int(k)
        if k < 1 or self.n_blocks % k:
            raise ValueError(f"k must divide n_blocks={self.n_blocks}, "
                             f"got {k}")
        per = self.n_blocks // k
        half = self._n >> 1
        side_off = np.arange(self._n, dtype=np.int64) % half
        blocks = side_off >> self.block_bits
        return (blocks // per).astype(np.int32)

    planted_cut_ratio = SbmHashStream.planted_cut_ratio
