"""Compact sparse vertex ids (SNAP graphs often have gaps).

The pipeline sizes every vertex-indexed table by ``max_id + 1``
(SURVEY.md §2 #1's streaming contract), so a graph whose ids are sparse
— e.g. crawl datasets keyed by hash — pays memory for ids that never
occur. This tool renumbers vertices densely in TWO streaming passes and
writes the inverse map so partitions translate back:

    python -m sheep_tpu.io.relabel sparse.edges dense.bin32
    # -> dense.bin32 (edges, ids in [0, V_used))
    # -> dense.bin32.map (raw little-endian int64: new id -> old id)

Memory is O(max_id * 5/8 + chunk): a bitmap of used ids (max_id/8
bytes) plus a byte-granular uint32 rank prefix (max_id/2 bytes) — the
dense id of old id i is ``prefix[i >> 3] + popcount(bits below i&7)``,
so no O(max_id)-sized int64 translation table is ever materialized
(~1.3 GB at the int32 id ceiling, vs ~16 GB for the naive table).

The mapping preserves id ORDER (old ids ascending -> new ids ascending),
so degree ties break identically before/after when the tie-break is by
id. Partition results on the dense graph map back with
``old_part[map[new]] = part[new]``.
"""

from __future__ import annotations

import os
import sys

import numpy as np

# bits_below[byte, bit] = popcount of byte's bits strictly below `bit`
_BITS_BELOW = np.array(
    [[bin(b & ((1 << bit) - 1)).count("1") for bit in range(8)]
     for b in range(256)], dtype=np.uint8)
_POPCNT = np.array([bin(b).count("1") for b in range(256)], dtype=np.uint8)


def used_id_bitmap(stream, chunk_edges: int = 1 << 22) -> np.ndarray:
    """Pass 1: bitmap of ids that occur as either endpoint
    (uint8[ceil((max_id+1)/8)]). Rejects negative ids loudly — Python's
    negative indexing would otherwise corrupt the bitmap silently."""
    n = stream.num_vertices  # max id + 1 (streaming pass if unknown)
    bitmap = np.zeros((n + 7) // 8, dtype=np.uint8)
    for chunk in stream.chunks(chunk_edges):
        ids = np.asarray(chunk, dtype=np.int64).ravel()
        if ids.size and int(ids.min()) < 0:
            raise ValueError("negative vertex id in stream")
        np.bitwise_or.at(bitmap, ids >> 3,
                         np.left_shift(np.uint8(1),
                                       (ids & 7).astype(np.uint8)))
    return bitmap


def _rank_prefix(bitmap: np.ndarray) -> tuple[np.ndarray, int]:
    """(uint32 exclusive prefix of per-byte popcounts, total used)."""
    counts = _POPCNT[bitmap]
    total = int(counts.sum(dtype=np.int64))
    if total >= 1 << 32:
        # the uint32 rank prefix caps relabeling at 2^32 - 1 USED ids
        # regardless of output format (old ids may still exceed 2^32 —
        # that is the sparse-id case relabeling exists for)
        raise ValueError("more than 2^32 - 1 used ids; relabeling's rank "
                         "prefix is uint32 (dense output ids would not "
                         "fit .bin32 either)")
    prefix = np.zeros(len(bitmap), dtype=np.uint32)
    np.cumsum(counts[:-1], out=prefix[1:], dtype=np.uint32)
    return prefix, total


def relabel_to(stream, out_path: str, map_path: str | None = None,
               chunk_edges: int = 1 << 22):
    """Rewrite ``stream`` with dense ids; returns (v_used, v_old, edges).

    ``out_path`` format by extension (.bin32/.bin64); the new->old map
    lands at ``map_path`` (default ``out_path + '.map'``) as a raw
    little-endian int64 array — NOT .pbin, whose int32 cells could not
    hold old ids >= 2^31, the very graphs relabeling exists for.

    Ceiling: the number of USED ids must stay below 2^32 (the rank
    prefix is uint32) — for either output format; old ids themselves may
    go up to 2^63 - 1. A graph with >= 2^32 distinct vertices is already
    dense territory where relabeling buys nothing."""
    from sheep_tpu.io import formats

    # fail on a bad destination BEFORE the full pass-1 stream scan
    fmt = formats.detect_format(out_path)
    if fmt not in ("bin32", "bin64"):
        raise ValueError("relabel writes binary edge lists "
                         "(.bin32/.bin64); got " + fmt)
    bitmap = used_id_bitmap(stream, chunk_edges)
    # _rank_prefix enforces the v_used < 2^32 ceiling (uint32 prefix);
    # dense output ids therefore always fit .bin32's u4 cells
    prefix, v_used = _rank_prefix(bitmap)
    n_old = stream.num_vertices
    dtype = np.dtype("<u4") if fmt == "bin32" else np.dtype("<u8")

    def rank(ids: np.ndarray) -> np.ndarray:
        byte, bit = ids >> 3, (ids & 7).astype(np.uint8)
        return (prefix[byte].astype(np.int64)
                + _BITS_BELOW[bitmap[byte], bit])

    edges = 0
    out_tmp, map_tmp = out_path + ".tmp", (map_path or out_path + ".map") \
        + ".tmp"
    with open(out_tmp, "wb") as f:
        for chunk in stream.chunks(chunk_edges):
            e = rank(np.asarray(chunk, dtype=np.int64))
            np.ascontiguousarray(e, dtype=dtype).tofile(f)
            edges += len(e)
    # new -> old map, streamed in bitmap blocks so no O(v_used) array
    # beyond the block is held
    with open(map_tmp, "wb") as f:
        block = 1 << 20  # bitmap bytes per block = 2^23 ids
        for off in range(0, len(bitmap), block):
            bits = np.unpackbits(bitmap[off:off + block],
                                 bitorder="little").astype(bool)
            old = np.flatnonzero(bits) + (off << 3)
            old[old < n_old].astype("<i8").tofile(f)
    # install both files only after both are complete; the map goes
    # first so a crash between the two replaces leaves old edges + new
    # map (detectably mismatched sizes) rather than new edges silently
    # paired with a stale map
    os.replace(map_tmp, map_path or out_path + ".map")
    os.replace(out_tmp, out_path)
    return v_used, n_old, edges


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) not in (2, 3):
        print("usage: python -m sheep_tpu.io.relabel INPUT OUTPUT.bin32 "
              "[MAP]", file=sys.stderr)
        return 2
    from sheep_tpu.io.edgestream import open_input

    stream = open_input(argv[0])
    v_used, n_old, edges = relabel_to(
        stream, argv[1], argv[2] if len(argv) == 3 else None)
    print(f"wrote {argv[1]}: {edges} edges, {v_used} used ids "
          f"(of {n_old} in the old id space, "
          f"{100 * (1 - v_used / max(n_old, 1)):.1f}% gap)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
