"""Memory-mapped CSR graph storage (SURVEY.md §2 #13, §1 storage engine).

The upstream system builds on a memory-mapped multiversion CSR store
(LLAMA) as its in-memory graph representation [PAPER]/[UNVERIFIED —
reference mount empty, SURVEY.md §0]. The partitioning pipeline itself
only ever *streams* edges, so the rebuild descoped a full multiversion
store (SURVEY.md §7 "What NOT to build"); what this module provides is
the capability that matters at the EdgeStream boundary: a **single
snapshot, mmap-backed CSR on-disk format** that

- round-trips the exact edge multiset of any EdgeStream source,
- answers ``num_vertices`` / ``num_edges`` in O(1) from the header,
- seeks any edge-id range in O(log V) (one ``searchsorted`` on the
  mmapped ``indptr``) — so chunked streaming, round-robin sharding and
  checkpoint resume cost the same as the raw binary formats,
- serves adjacency queries (``neighbors(u)``, ``out_degree``) that the
  flat edge-list formats cannot answer without a full scan.

Layout (all little-endian, fixed 32-byte header)::

    magic    8s   = b"SHEEPCSR"
    version  u32  = 1
    flags    u32    bit0: indices are int64 (else int32)
    n_vertices u64
    n_edges    u64
    indptr   int64[n_vertices + 1]
    indices  int32|int64[n_edges]

Vertex ``u`` owns edge ids ``[indptr[u], indptr[u+1])``; ``indices``
holds the destination of each edge. Source vertices are implicit — the
~50% size saving vs ``.bin64`` is the point of CSR on disk. Duplicate
edges and self-loops are preserved verbatim, so conversion is lossless
up to edge *order* (edges regroup under their source vertex, input
order preserved within a vertex). The partition pipeline is invariant
to stream order — the elimination forest is a function of the
constraint multiset (ops/elim.py), degrees/scores are order-free sums —
so a converted graph partitions bit-identically to its source
(tests/test_csr.py asserts this end-to-end).
"""

from __future__ import annotations

import os
import struct
import sys
from typing import Optional

import numpy as np

MAGIC = b"SHEEPCSR"
VERSION = 1
_HEADER = struct.Struct("<8sIIQQ")
HEADER_BYTES = _HEADER.size  # 32
FLAG_WIDE = 1  # indices stored as int64 (graphs with >= 2^31 vertices)


class CsrHeader:
    __slots__ = ("n_vertices", "n_edges", "wide")

    def __init__(self, n_vertices: int, n_edges: int, wide: bool):
        self.n_vertices = n_vertices
        self.n_edges = n_edges
        self.wide = wide

    @property
    def indptr_offset(self) -> int:
        return HEADER_BYTES

    @property
    def indices_offset(self) -> int:
        return HEADER_BYTES + 8 * (self.n_vertices + 1)

    @property
    def indices_dtype(self) -> np.dtype:
        return np.dtype("<i8") if self.wide else np.dtype("<i4")


def read_header(path: str) -> CsrHeader:
    with open(path, "rb") as f:
        raw = f.read(HEADER_BYTES)
    if len(raw) < HEADER_BYTES:
        raise ValueError(f"{path!r}: truncated CSR header")
    magic, version, flags, n, e = _HEADER.unpack(raw)
    if magic != MAGIC:
        raise ValueError(f"{path!r}: not a SHEEPCSR file (magic {magic!r})")
    if version != VERSION:
        raise ValueError(f"{path!r}: CSR version {version} "
                         f"(this build reads {VERSION})")
    return CsrHeader(n, e, bool(flags & FLAG_WIDE))


class CsrGraph:
    """Read-only mmap view of a ``.csr`` file.

    Opens lazily and holds the maps only while alive; EdgeStream's
    chunk iterators open/close one per pass, keeping the no-persistent-fd
    contract of the other formats.
    """

    def __init__(self, path: str):
        self.path = path
        self.header = read_header(path)
        h = self.header
        self._indptr = np.memmap(path, dtype="<i8", mode="r",
                                 offset=h.indptr_offset,
                                 shape=(h.n_vertices + 1,))
        self._indices = np.memmap(path, dtype=h.indices_dtype, mode="r",
                                  offset=h.indices_offset,
                                  shape=(h.n_edges,))

    # -- metadata ---------------------------------------------------------
    @property
    def n_vertices(self) -> int:
        return self.header.n_vertices

    @property
    def n_edges(self) -> int:
        return self.header.n_edges

    @property
    def indptr(self) -> np.ndarray:
        return self._indptr

    # -- adjacency --------------------------------------------------------
    def out_degrees(self) -> np.ndarray:
        return np.diff(self._indptr)

    def out_degree(self, u: int) -> int:
        return int(self._indptr[u + 1] - self._indptr[u])

    def neighbors(self, u: int) -> np.ndarray:
        return np.asarray(
            self._indices[self._indptr[u]:self._indptr[u + 1]],
            dtype=np.int64)

    def arcs_from(self, vertices: np.ndarray) -> tuple:
        """Batch adjacency gather: all arcs leaving ``vertices``, as
        ``(src, dst)`` int64 arrays (``len == sum of out-degrees``).
        One vectorized fancy-index over the mmapped ``indices`` region —
        the primitive the O(Δ) incremental scorer leans on to touch
        only the changed-vertex neighborhoods instead of re-streaming
        every edge."""
        vs = np.asarray(vertices, dtype=np.int64).reshape(-1)
        if not len(vs):
            z = np.zeros(0, dtype=np.int64)
            return z, z
        indptr = self._indptr
        starts = np.asarray(indptr[vs], dtype=np.int64)
        counts = np.asarray(indptr[vs + 1], dtype=np.int64) - starts
        total = int(counts.sum())
        if total == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        src = np.repeat(vs, counts)
        # flat edge-ids: per-vertex start broadcast along its degree run
        cum = np.zeros(len(vs), dtype=np.int64)
        np.cumsum(counts[:-1], out=cum[1:])
        eid = (np.arange(total, dtype=np.int64)
               - np.repeat(cum, counts) + np.repeat(starts, counts))
        dst = np.asarray(self._indices[eid], dtype=np.int64)
        return src, dst

    # -- edge-id addressing (the EdgeStream seek primitive) ---------------
    def edge_slice(self, start: int, end: int) -> np.ndarray:
        """Edges with ids in ``[start, end)`` as an (end-start, 2) int64
        array. O(log V) to locate the vertex span + O(output)."""
        e = self.header.n_edges
        start = max(0, min(start, e))
        end = max(start, min(end, e))
        if end == start:
            return np.zeros((0, 2), dtype=np.int64)
        indptr = self._indptr
        lo = int(np.searchsorted(indptr, start, side="right")) - 1
        hi = int(np.searchsorted(indptr, end, side="left")) - 1
        starts = np.maximum(np.asarray(indptr[lo:hi + 1], dtype=np.int64),
                            start)
        ends = np.minimum(np.asarray(indptr[lo + 1:hi + 2], dtype=np.int64),
                          end)
        out = np.empty((end - start, 2), dtype=np.int64)
        out[:, 0] = np.repeat(np.arange(lo, hi + 1, dtype=np.int64),
                              ends - starts)
        out[:, 1] = self._indices[start:end]
        return out

    def close(self) -> None:
        # numpy memmaps release on gc; drop refs eagerly so a pass's
        # mappings do not outlive it
        self._indptr = self._indices = None  # type: ignore[assignment]


def write_csr(path: str, stream, n_vertices: Optional[int] = None,
              chunk_edges: int = 1 << 22) -> CsrHeader:
    """Convert any EdgeStream-like source to a ``.csr`` file.

    Two streaming passes, O(V) host memory (degree counters + write
    cursors), edges written straight into the mmapped indices region —
    the same bounded-footprint discipline as the partition pipeline, so
    conversion scales to the billion-edge soak class.

    The write is atomic: everything lands in ``path + '.tmp'`` and is
    renamed over ``path`` only when complete.
    """
    n = stream.num_vertices if n_vertices is None else n_vertices
    # pass 1: out-degrees
    deg = np.zeros(n, dtype=np.int64)
    e_total = 0
    for chunk in stream.chunks(chunk_edges):
        if len(chunk) == 0:
            continue
        if int(chunk.min()) < 0 or int(chunk.max()) >= n:
            raise ValueError(f"edge endpoint out of range [0, {n})")
        u = np.asarray(chunk[:, 0], dtype=np.int64)
        deg += np.bincount(u, minlength=n)
        e_total += len(chunk)
    wide = n > np.iinfo(np.int32).max
    header = CsrHeader(n, e_total, wide)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=indptr[1:])

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_HEADER.pack(MAGIC, VERSION, FLAG_WIDE if wide else 0,
                             n, e_total))
        indptr.astype("<i8", copy=False).tofile(f)
        f.truncate(header.indices_offset +
                   e_total * header.indices_dtype.itemsize)
    # pass 2: scatter destinations into each source's slot range; cursor
    # tracks the next free slot per vertex, per-chunk stable sort keeps
    # a vertex's input edge order
    cursor = indptr[:-1].copy()
    if e_total:
        mm = np.memmap(tmp, dtype=header.indices_dtype, mode="r+",
                       offset=header.indices_offset, shape=(e_total,))
        for chunk in stream.chunks(chunk_edges):
            if len(chunk) == 0:
                continue
            u = np.asarray(chunk[:, 0], dtype=np.int64)
            v = np.asarray(chunk[:, 1], dtype=np.int64)
            order = np.argsort(u, kind="stable")
            us = u[order]
            # rank of each edge within its vertex group in this chunk
            boundary = np.empty(len(us), dtype=bool)
            boundary[0] = True
            np.not_equal(us[1:], us[:-1], out=boundary[1:])
            group_start = np.maximum.accumulate(
                np.where(boundary, np.arange(len(us)), 0))
            rank = np.arange(len(us)) - group_start
            mm[cursor[us] + rank] = v[order]
            uniq, counts = us[boundary], np.diff(
                np.append(np.flatnonzero(boundary), len(us)))
            cursor[uniq] += counts
        mm.flush()
        del mm
    if not np.array_equal(cursor, indptr[1:]):
        raise RuntimeError("CSR conversion: stream changed between passes")
    os.replace(tmp, path)
    return header


def main(argv=None) -> int:
    """``python -m sheep_tpu.io.csr INPUT OUTPUT.csr [NUM_VERTICES]`` —
    convert any supported input (file path or synthetic spec) to CSR."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) not in (2, 3):
        print(__doc__.splitlines()[0], file=sys.stderr)
        print("usage: python -m sheep_tpu.io.csr INPUT OUTPUT.csr "
              "[NUM_VERTICES]", file=sys.stderr)
        return 2
    from sheep_tpu.io.edgestream import open_input

    n = int(argv[2]) if len(argv) == 3 else None
    stream = open_input(argv[0], n_vertices=n)
    h = write_csr(argv[1], stream)
    print(f"wrote {argv[1]}: {h.n_vertices} vertices, {h.n_edges} edges, "
          f"{'int64' if h.wide else 'int32'} indices")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
