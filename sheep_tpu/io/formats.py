"""On-disk graph formats (SURVEY.md §2 #2).

The reference keeps formats byte-stable across backends [NORTH-STAR]; we
define the standard interchange formats a SNAP-era partitioner consumes:

- **text edge list** (``.edges``/``.txt``/``.el``): one ``u v`` pair per
  line, whitespace separated, ``#`` comment lines ignored (SNAP style).
- **binary edge list**: raw little-endian pairs, no header;
  ``.bin32`` = uint32 pairs, ``.bin64`` = uint64 pairs. Offsets are stable,
  so byte ranges shard trivially across workers/hosts.
- **partition map**: ``.parts`` text (one part id per line, line i = vertex
  i) or ``.pbin`` raw little-endian int32 array.

All readers/writers round-trip byte-identically (golden tests in
``tests/test_formats.py``).
"""

from __future__ import annotations

import os

import numpy as np

TEXT_EXTS = (".edges", ".txt", ".el", ".snap")
BIN32_EXTS = (".bin32", ".bin")
BIN64_EXTS = (".bin64",)
CSR_EXTS = (".csr",)


def detect_format(path: str) -> str:
    base, ext = os.path.splitext(path)
    ext = ext.lower()
    if ext == ".gz":
        inner = os.path.splitext(base)[1].lower()
        if inner in TEXT_EXTS:
            return "text-gz"  # how SNAP distributes graphs
        raise ValueError(
            f"gzip is supported for text edge lists only, not {inner!r} "
            f"({path!r}); decompress binary formats first")
    if ext in TEXT_EXTS:
        return "text"
    if ext in BIN32_EXTS:
        return "bin32"
    if ext in BIN64_EXTS:
        return "bin64"
    if ext in CSR_EXTS:
        return "csr"
    raise ValueError(f"unknown graph format for {path!r} (ext {ext!r})")


def parse_text_line(line: str):
    """Parse one edge-list line -> (u, v) or None.

    Policy (matches the native parser sheep_parse_text): comments
    (#/%), blanks, and malformed lines are skipped, extra columns ignored.
    """
    line = line.strip()
    if not line or line.startswith(("#", "%")):
        return None
    parts = line.split()
    if len(parts) < 2:
        return None
    try:
        return int(parts[0]), int(parts[1])
    except ValueError:
        return None


from contextlib import contextmanager


@contextmanager
def _open_text(path: str, mode: str, gz: bool | None = None):
    """``gz`` None = sniff the extension; an explicit caller ``fmt``
    must override sniffing (as it does for the binary formats).

    Writes go through GzipFile(fileobj=..., mtime=0): the module
    contract is byte-identical round-trips, and gzip's header would
    otherwise embed wall-clock time AND the basename (FNAME), making
    identical content hash differently per path/moment."""
    if gz is None:
        gz = path.lower().endswith(".gz")
    if gz:
        import gzip
        import io

        with open(path, mode + "b") as raw, \
                gzip.GzipFile(filename="", fileobj=raw, mode=mode + "b",
                              mtime=0) as gzf, \
                io.TextIOWrapper(gzf) as f:
            yield f
    else:
        with open(path, mode) as f:
            yield f


def read_text_edges(path: str, gz: bool | None = None) -> np.ndarray:
    """Read a SNAP-style text edge list (plain or gzip) into an (E, 2)
    int64 array."""
    rows = []
    with _open_text(path, "r", gz) as f:
        for line in f:
            pair = parse_text_line(line)
            if pair is not None:
                rows.append(pair)
    if not rows:
        return np.zeros((0, 2), dtype=np.int64)
    return np.asarray(rows, dtype=np.int64)


def write_text_edges(path: str, edges: np.ndarray,
                     gz: bool | None = None) -> None:
    with _open_text(path, "w", gz) as f:
        for u, v in np.asarray(edges, dtype=np.int64):
            f.write(f"{u} {v}\n")


def read_binary_edges(path: str, dtype) -> np.ndarray:
    flat = np.fromfile(path, dtype=dtype)
    if flat.size % 2:
        raise ValueError(f"{path}: odd number of ints, not an edge list")
    return flat.reshape(-1, 2).astype(np.int64, copy=False)


def write_binary_edges(path: str, edges: np.ndarray, dtype) -> None:
    e = np.asarray(edges).reshape(-1, 2)
    info = np.iinfo(dtype)
    if len(e) and (e.min() < info.min or e.max() > info.max):
        raise ValueError(
            f"vertex id out of range for {dtype}: "
            f"[{e.min()}, {e.max()}] vs [{info.min}, {info.max}]"
        )
    np.ascontiguousarray(e, dtype=dtype).tofile(path)


def read_edges(path: str, fmt: str | None = None) -> np.ndarray:
    """Materialize the full edge list (small graphs / tests only — the
    streaming path is :class:`sheep_tpu.io.edgestream.EdgeStream`)."""
    fmt = fmt or detect_format(path)
    if fmt in ("text", "text-gz"):
        return read_text_edges(path, gz=(fmt == "text-gz"))
    if fmt == "bin32":
        return read_binary_edges(path, np.dtype("<u4"))
    if fmt == "bin64":
        return read_binary_edges(path, np.dtype("<u8"))
    raise ValueError(f"unknown format {fmt!r}")


def write_edges(path: str, edges: np.ndarray, fmt: str | None = None) -> None:
    fmt = fmt or detect_format(path)
    if fmt in ("text", "text-gz"):
        write_text_edges(path, edges, gz=(fmt == "text-gz"))
    elif fmt == "bin32":
        write_binary_edges(path, edges, np.dtype("<u4"))
    elif fmt == "bin64":
        write_binary_edges(path, edges, np.dtype("<u8"))
    else:
        raise ValueError(f"unknown format {fmt!r}")


def write_partition(path: str, assignment: np.ndarray) -> None:
    if path.endswith(".pbin"):
        np.ascontiguousarray(assignment, dtype=np.dtype("<i4")).tofile(path)
    else:
        with open(path, "w") as f:
            for p in assignment:
                f.write(f"{int(p)}\n")


def read_partition(path: str) -> np.ndarray:
    if path.endswith(".pbin"):
        return np.fromfile(path, dtype=np.dtype("<i4")).astype(np.int32)
    with open(path) as f:
        return np.array([int(x) for x in f.read().split()], dtype=np.int32)
