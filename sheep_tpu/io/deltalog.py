"""Delta log — the append-log input format for MUTATING graphs
(ISSUE 15 tentpole, ROADMAP item 2).

A production graph is not a frozen file: edges arrive (and leave)
continuously, and until this format existed a single new edge meant a
full O(E) rebuild. The delta log is the missing input layer: an
append-only binary log of epoch-stamped ADD records and tombstone
(DELETE) records over a *base* input, self-describing (the header
carries the base input spec), replayable, and damage-hardened under
the same ``SHEEP_IO_POLICY`` quarantine-or-raise contract as
``io/edgestream.py``.

Layout::

    header:  magic b"SHEEPDLG" | u32 version | u32 header_len |
             [v2+: u64 epoch_floor] |
             base_spec utf-8 (header_len - fixed bytes)
    records: 24-byte little-endian records, appended forever:
             u64 u | u64 v | u32 epoch | u16 op | u16 flags

Version 1 has no ``epoch_floor`` (implicitly 0). Version 2 carries
the COMPACTION FLOOR (ISSUE 17): :meth:`DeltaLogWriter.rewrite_base`
materializes the surviving multiset into a fresh base artifact and
rewrites the log to an empty v2 log over it with ``epoch_floor`` =
the last applied epoch, so replay history and the tombstone filter
stay O(recent) while epoch numbering (the served idempotency key)
keeps advancing monotonically across the rewrite. Writers emit v1
whenever the floor is 0, so un-compacted logs stay readable by v1
readers.

``op`` is 0 (ADD) or 1 (DEL); ``epoch`` is non-decreasing — one epoch
is one applied delta batch (the unit of durability and idempotency for
the served ``update`` verb). A DEL tombstones ONE occurrence of the
undirected edge {u, v} from the current multiset, cancelling a pending
ADD first and a base edge otherwise.

Damage contract (tests/test_edgestream.py TestDeltaLogDamage):

- a torn trailing record ((size - header_len) % 24 != 0) is never
  silently folded: strict raises :class:`CorruptStreamError` with a
  diagnosis, quarantine drops the torn bytes + emits a
  ``chunk_quarantined`` trace event and continues over the intact
  prefix;
- a mid-log short read (the log shrank under a live reader) follows
  the same contract;
- an epoch that DECREASES mid-log is corruption, not history — same
  contract, intact prefix only.

:class:`DeltaLogStream` is the one-shot view: an EdgeStream-compatible
stream of the SURVIVING multiset (base minus tombstones plus surviving
adds), opened via the ``delta:LOG[@EPOCH]`` input spec
(:func:`sheep_tpu.io.edgestream.open_input`). Its documented
**anchored-order semantics**: the elimination order of a delta-log
build is derived from the BASE segment's degree table
(``order_anchor`` / :meth:`DeltaLogStream.anchor_chunks`), not the
union's — which is exactly what makes the incremental path
(:mod:`sheep_tpu.incremental`) bit-identical to this one-shot build:
a converged carried table absorbs each epoch as just another segment
batch under the same order (the fixpoint is order-independent in the
constraint multiset), so incremental == one-shot by the merge
property, not by luck. Compaction re-anchors (fresh survivor degrees)
— see ``incremental.compact``.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

MAGIC = b"SHEEPDLG"
VERSION = 2
HEADER_FIXED = 16       # magic + u32 version + u32 header_len
HEADER_FIXED_V2 = 24    # ... + u64 epoch_floor

OP_ADD = 0
OP_DEL = 1

RECORD_DTYPE = np.dtype([("u", "<u8"), ("v", "<u8"),
                         ("epoch", "<u4"), ("op", "<u2"),
                         ("flags", "<u2")])
RECORD_BYTES = RECORD_DTYPE.itemsize  # 24
MAX_BASE_SPEC_BYTES = 1 << 16


def _quarantine_or_raise(msg: str, **fields) -> None:
    from sheep_tpu.io.edgestream import _quarantine_or_raise as q

    q(msg, **fields)


def write_header(path: str, base_spec: str,
                 epoch_floor: int = 0) -> None:
    """Write a fresh log header (fsync'd). ``epoch_floor`` > 0 emits
    the v2 layout; a floor of 0 stays on the v1 bytes so un-compacted
    logs remain readable by v1 readers."""
    spec_b = base_spec.encode("utf-8")
    if not spec_b or len(spec_b) > MAX_BASE_SPEC_BYTES:
        raise ValueError(f"bad delta-log base spec ({len(spec_b)} bytes)")
    epoch_floor = int(epoch_floor)
    if epoch_floor < 0:
        raise ValueError(f"negative epoch floor {epoch_floor}")
    version = 2 if epoch_floor else 1
    fixed = HEADER_FIXED_V2 if epoch_floor else HEADER_FIXED
    header_len = fixed + len(spec_b)
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint32(version).tobytes())
        f.write(np.uint32(header_len).tobytes())
        if epoch_floor:
            f.write(np.uint64(epoch_floor).tobytes())
        f.write(spec_b)
        f.flush()
        os.fsync(f.fileno())


def read_header(path: str) -> dict:
    """{"version", "base_spec", "header_len", "epoch_floor"}; raises
    ValueError on a file that is not a delta log (wrong magic /
    impossible header). ``epoch_floor`` is 0 for v1 logs."""
    with open(path, "rb") as f:
        fixed = f.read(HEADER_FIXED)
        if len(fixed) < HEADER_FIXED or fixed[:8] != MAGIC:
            raise ValueError(f"{path}: not a sheep delta log "
                             f"(bad magic)")
        version = int(np.frombuffer(fixed[8:12], "<u4")[0])
        header_len = int(np.frombuffer(fixed[12:16], "<u4")[0])
        if version > VERSION:
            raise ValueError(f"{path}: delta log v{version} is newer "
                             f"than this reader (v{VERSION})")
        fixed_len = HEADER_FIXED_V2 if version >= 2 else HEADER_FIXED
        if not (fixed_len <= header_len
                <= fixed_len + MAX_BASE_SPEC_BYTES):
            raise ValueError(f"{path}: impossible delta-log header "
                             f"length {header_len}")
        epoch_floor = 0
        if version >= 2:
            floor_b = f.read(8)
            if len(floor_b) != 8:
                raise ValueError(f"{path}: truncated delta-log header")
            epoch_floor = int(np.frombuffer(floor_b, "<u8")[0])
        spec_b = f.read(header_len - fixed_len)
        if len(spec_b) != header_len - fixed_len:
            raise ValueError(f"{path}: truncated delta-log header")
    return {"version": version,
            "base_spec": spec_b.decode("utf-8"),
            "header_len": header_len,
            "epoch_floor": epoch_floor}


class DeltaLogWriter:
    """Appender: one :meth:`append` batch per (op, epoch); epochs are
    non-decreasing, auto-assigned as last+1 when not given. Appends are
    fsync'd by default — an acked epoch is the durability promise a
    tenant streams deltas against."""

    def __init__(self, path: str, base_spec: Optional[str] = None):
        self.path = path
        if os.path.exists(path) and os.path.getsize(path) > 0:
            hdr = read_header(path)
            if base_spec is not None and base_spec != hdr["base_spec"]:
                raise ValueError(
                    f"{path} already logs deltas over "
                    f"{hdr['base_spec']!r}, not {base_spec!r}")
            self.base_spec = hdr["base_spec"]
            self.epoch_floor = int(hdr.get("epoch_floor", 0))
            # resuming an appender needs ONE number: the final
            # record's epoch (epochs are validated non-decreasing, so
            # the tail record holds the max). O(1) seek on an intact
            # log; only a damaged body pays the full validated read.
            body = os.path.getsize(path) - hdr["header_len"]
            if body and body % RECORD_BYTES == 0:
                with open(path, "rb") as f:
                    f.seek(hdr["header_len"] + body - RECORD_BYTES)
                    tail = np.fromfile(f, dtype=RECORD_DTYPE, count=1)
                self.last_epoch = max(int(tail["epoch"][0]),
                                      self.epoch_floor)
            else:
                recs = DeltaLogReader(path).records()
                self.last_epoch = max(
                    int(recs["epoch"][-1]) if len(recs) else 0,
                    self.epoch_floor)
        else:
            if base_spec is None:
                raise ValueError("a new delta log needs base_spec")
            write_header(path, base_spec)
            self.base_spec = base_spec
            self.epoch_floor = 0
            self.last_epoch = 0
        self._f = open(path, "ab")

    def append(self, edges, op: int = OP_ADD,
               epoch: Optional[int] = None, fsync: bool = True) -> int:
        """Append one batch of (m, 2) edges as ``op`` records stamped
        ``epoch`` (default: a fresh epoch). Returns the epoch used."""
        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        if op not in (OP_ADD, OP_DEL):
            raise ValueError(f"bad delta op {op!r}")
        if np.any(e < 0):
            raise ValueError("delta edges must have non-negative ids")
        if epoch is None:
            epoch = self.last_epoch + 1
        epoch = int(epoch)
        if epoch < self.last_epoch:
            raise ValueError(f"epoch {epoch} < last epoch "
                             f"{self.last_epoch} (epochs never rewind)")
        rec = np.zeros(len(e), dtype=RECORD_DTYPE)
        rec["u"] = e[:, 0].astype(np.uint64)
        rec["v"] = e[:, 1].astype(np.uint64)
        rec["epoch"] = np.uint32(epoch)
        rec["op"] = np.uint16(op)
        self._f.write(rec.tobytes())
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())
        self.last_epoch = epoch
        return epoch

    def append_epoch(self, adds=None, dels=None) -> int:
        """Convenience: one new epoch carrying adds then dels. The
        LAST batch written carries the fsync (one durable point per
        epoch — an empty dels array must not strand the adds
        unsynced)."""
        epoch = self.last_epoch + 1
        has_adds = adds is not None and len(adds)
        has_dels = dels is not None and len(dels)
        if has_adds:
            self.append(adds, OP_ADD, epoch=epoch,
                        fsync=not has_dels)
        if has_dels:
            self.append(dels, OP_DEL, epoch=epoch)
        self.last_epoch = epoch
        return epoch

    def rewrite_base(self, base_out: str,
                     n_vertices: Optional[int] = None) -> str:
        """Full log compaction (ISSUE 17 tentpole): materialize the
        SURVIVING multiset (base ∪ log) into a fresh CSR base artifact
        at ``base_out``, then rewrite this log in place to an empty v2
        log over that artifact with ``epoch_floor`` = the last applied
        epoch. Replay history and the tombstone filter become
        O(recent); epoch numbering keeps advancing (the next appended
        epoch is ``floor + 1``), so served idempotency keys survive
        the rewrite.

        Crash discipline (same tmp + rename story as resultstore): the
        base artifact lands atomically FIRST; the log header rewrite
        lands atomically second and is the commit point. Kill -9
        before it: old base_spec + full log, untouched. After it:
        fresh pair. Nothing in between is ever visible. The old base
        artifact is NOT deleted here — the caller owns old-artifact
        cleanup because only it knows whether the old base is a
        user-supplied input or a previous rewrite's product."""
        from sheep_tpu.io import csr as csr_mod

        stream = DeltaLogStream(self.path)
        n = stream.num_vertices if n_vertices is None \
            else int(n_vertices)
        csr_mod.write_csr(base_out, stream, n_vertices=n)
        floor = max(self.last_epoch, stream.epoch)
        tmp = self.path + ".rewrite.tmp"
        write_header(tmp, base_out, epoch_floor=floor)
        self.close()
        os.replace(tmp, self.path)
        dfd = os.open(os.path.dirname(os.path.abspath(self.path))
                      or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._f = open(self.path, "ab")
        self.base_spec = base_out
        self.epoch_floor = floor
        self.last_epoch = floor
        return base_out

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def __enter__(self) -> "DeltaLogWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class DeltaLogReader:
    """Validated record access (quarantine-or-raise on damage; bounded
    transient-read retry like every physical read in io/)."""

    def __init__(self, path: str):
        self.path = path
        self.header = read_header(path)
        self._records: Optional[np.ndarray] = None

    def records(self) -> np.ndarray:
        """The validated record array (structured RECORD_DTYPE). Under
        quarantine, damage truncates to the intact prefix; under
        strict it raises. Cached per reader."""
        if self._records is not None:
            return self._records
        from sheep_tpu.io.edgestream import (_read_retry_policy,
                                             _retrying)

        hlen = self.header["header_len"]
        size = os.path.getsize(self.path)
        body = size - hlen
        torn = body % RECORD_BYTES
        if torn:
            _quarantine_or_raise(
                f"{self.path}: {body} delta-log body bytes is not a "
                f"multiple of the {RECORD_BYTES}-byte record "
                f"({torn} torn trailing bytes)",
                path=self.path, torn_bytes=int(torn))
        count = body // RECORD_BYTES
        policy = _read_retry_policy()

        def _read():
            with open(self.path, "rb") as f:
                f.seek(hlen)
                return np.fromfile(f, dtype=RECORD_DTYPE, count=count)

        recs = _retrying(policy, _read, f"read {self.path}")
        if len(recs) != count:
            # mid-log short read: the log shrank under us
            _quarantine_or_raise(
                f"{self.path}: short read (wanted {count} delta "
                f"records, got {len(recs)}) — log truncated mid-pass",
                path=self.path, expected=int(count), got=int(len(recs)))
        if len(recs):
            ep = recs["epoch"].astype(np.int64)
            bad = np.nonzero(np.diff(ep) < 0)[0]
            if len(bad):
                at = int(bad[0]) + 1
                _quarantine_or_raise(
                    f"{self.path}: epoch rewinds at record {at} "
                    f"({int(ep[at])} after {int(ep[at - 1])}) — "
                    f"corrupt log; keeping the intact prefix",
                    path=self.path, record=at)
                recs = recs[:at]
            bad_op = np.nonzero(~np.isin(recs["op"], (OP_ADD, OP_DEL)))[0]
            if len(bad_op):
                at = int(bad_op[0])
                _quarantine_or_raise(
                    f"{self.path}: unknown delta op "
                    f"{int(recs['op'][at])} at record {at}; keeping "
                    f"the intact prefix",
                    path=self.path, record=at)
                recs = recs[:at]
        self._records = recs
        return recs

    @property
    def max_epoch(self) -> int:
        recs = self.records()
        floor = int(self.header.get("epoch_floor", 0))
        return max(int(recs["epoch"][-1]) if len(recs) else 0, floor)

    def epochs(self, start_epoch: int = 0,
               up_to: Optional[int] = None) -> Iterator[tuple]:
        """Yield (epoch, adds (a, 2) int64, dels (d, 2) int64) per
        distinct epoch in (start_epoch, up_to]."""
        recs = self.records()
        if up_to is not None:
            recs = recs[recs["epoch"] <= up_to]
        recs = recs[recs["epoch"] > start_epoch]
        if not len(recs):
            return
        ep = recs["epoch"].astype(np.int64)
        bounds = np.nonzero(np.diff(ep))[0] + 1
        for seg in np.split(np.arange(len(recs)), bounds):
            r = recs[seg]
            e = np.stack([r["u"].astype(np.int64),
                          r["v"].astype(np.int64)], axis=1)
            is_add = r["op"] == OP_ADD
            yield int(r["epoch"][0]), e[is_add], e[~is_add]


# ----------------------------------------------------------------------
# multiset algebra shared by the one-shot stream and the incremental
# state: net effect of a record prefix, and tombstone filtering
# ----------------------------------------------------------------------
def net_effect(records) -> tuple:
    """(surviving_adds (a, 2) int64, base_tombstones (t, 2) int64) of a
    validated record array, replayed IN LOG ORDER: a DEL removes one
    occurrence of the edge from the multiset as it stood at that
    record — it cancels the latest still-pending EARLIER add, else it
    tombstones the base. A DEL can never reach forward and erase an
    add from a later epoch (deleting an absent edge removes nothing,
    then the later add re-introduces it) — exactly how the
    incremental path applies the same epochs, which is what keeps
    incremental == one-shot exact."""
    add_e = []           # (u, v) rows of adds, in order
    live: dict = {}      # norm key -> stack of indices into add_e
    tombs = []
    for rec in records:
        u, v = int(rec["u"]), int(rec["v"])
        if rec["op"] == OP_ADD:
            k = _norm_key(u, v)
            live.setdefault(k, []).append(len(add_e))
            add_e.append((u, v))
        else:
            k = _norm_key(u, v)
            stack = live.get(k)
            if stack:
                add_e[stack.pop()] = None  # cancel an EARLIER add
            else:
                tombs.append(k)
    surv = np.asarray([r for r in add_e if r is not None],
                      dtype=np.int64).reshape(-1, 2)
    tomb_arr = np.asarray(tombs, dtype=np.int64).reshape(-1, 2)
    return surv, tomb_arr


def _norm_key(u, v) -> tuple:
    u, v = int(u), int(v)
    return (u, v) if u <= v else (v, u)


def _key_iter(e):
    lo = np.minimum(e[:, 0], e[:, 1])
    hi = np.maximum(e[:, 0], e[:, 1])
    return zip(lo.tolist(), hi.tolist())


def cancel_adds(adds_list, dels) -> tuple:
    """Resolve a delete batch against pending ADD arrays, in order:
    each delete cancels the LATEST still-pending add of its undirected
    key; the remainder come back as base tombstones. This is the
    apply-time twin of :func:`net_effect`'s rule — both sides resolve
    deletes against the multiset AS IT STANDS, so a tombstone can
    never reach forward and eat an add from a later epoch. Returns
    (new_adds_list, base_tombstones (t, 2) int64)."""
    from collections import defaultdict

    stacks = defaultdict(list)
    for ai, arr in enumerate(adds_list):
        lo = np.minimum(arr[:, 0], arr[:, 1]).tolist()
        hi = np.maximum(arr[:, 0], arr[:, 1]).tolist()
        for ri, k in enumerate(zip(lo, hi)):
            stacks[k].append((ai, ri))
    keep = [np.ones(len(a), dtype=bool) for a in adds_list]
    rem = []
    for u, v in np.asarray(dels, np.int64).reshape(-1, 2).tolist():
        k = _norm_key(u, v)
        s = stacks.get(k)
        if s:
            ai, ri = s.pop()
            keep[ai][ri] = False
        else:
            rem.append(k)
    new_adds = [a[m] for a, m in zip(adds_list, keep) if m.any()]
    rem_arr = np.asarray(rem, dtype=np.int64).reshape(-1, 2)
    return new_adds, rem_arr


def filter_tombstones(chunks, tombs) -> Iterator[np.ndarray]:
    """Yield ``chunks`` with one occurrence per tombstone removed
    (undirected match, multiset semantics). ``tombs`` is an (t, 2)
    array; unmatched tombstones simply never fire (deleting an edge
    the graph does not have removes nothing)."""
    from collections import Counter

    if tombs is None or not len(tombs):
        for c in chunks:
            yield c
        return
    pending = Counter(_key_iter(np.asarray(tombs, np.int64)))
    lo_set = np.unique(np.minimum(tombs[:, 0], tombs[:, 1]))
    for c in chunks:
        e = np.asarray(c, dtype=np.int64).reshape(-1, 2)
        if sum(pending.values()) == 0 or not len(e):
            yield e
            continue
        lo = np.minimum(e[:, 0], e[:, 1])
        cand = np.nonzero(np.isin(lo, lo_set))[0]
        if not len(cand):
            yield e
            continue
        keep = np.ones(len(e), dtype=bool)
        for i in cand.tolist():
            k = _norm_key(e[i, 0], e[i, 1])
            if pending.get(k, 0) > 0:
                pending[k] -= 1
                keep[i] = False
        yield e[keep]


class DeltaLogStream:
    """EdgeStream-compatible one-shot view of base ∪ log (surviving
    multiset at ``up_to`` — default: the whole log), with the anchored
    elimination-order contract (module docstring).

    Single-shard streaming only: one process streams the log (the
    single-device backends, and the multi-device backends' lockstep
    ingest under one process — ISSUE 19). Multi-HOST meshes cannot
    byte-range an anchored log across processes and reject it up
    front."""

    order_anchor = True

    def __init__(self, path: str, up_to: Optional[int] = None,
                 n_vertices: Optional[int] = None):
        from sheep_tpu.io.edgestream import open_input

        self.path = path
        self.reader = DeltaLogReader(path)
        self.base_spec = self.reader.header["base_spec"]
        if self.base_spec.startswith("delta:"):
            raise ValueError(f"{path}: delta logs do not nest")
        self.base = open_input(self.base_spec)
        self.up_to = up_to
        floor = int(self.reader.header.get("epoch_floor", 0))
        if up_to is not None and up_to < floor:
            raise ValueError(
                f"{path}: epoch {up_to} predates the compaction "
                f"floor {floor} — that history was rewritten into "
                f"the base (rewrite_base)")
        recs = self.reader.records()
        if up_to is not None:
            recs = recs[recs["epoch"] <= up_to]
        self.epoch = max(int(recs["epoch"][-1]) if len(recs) else 0,
                         floor)
        self.adds, self.tombs = net_effect(recs)
        n = int(self.base.num_vertices)
        if len(self.adds):
            n = max(n, int(self.adds.max()) + 1)
        if len(self.tombs):
            n = max(n, int(self.tombs.max()) + 1)
        if n_vertices is not None:
            if n_vertices < n:
                raise ValueError(
                    f"--num-vertices {n_vertices} is below the "
                    f"delta-log vertex space ({n})")
            n = n_vertices
        self._n = n

    # -- EdgeStream-compatible surface ---------------------------------
    @property
    def num_vertices(self) -> int:
        return self._n

    @property
    def num_edges_cheap(self) -> Optional[int]:
        base = self.base.num_edges_cheap
        if base is None:
            return None
        # tombstones that never match remove nothing, so this is an
        # upper estimate only when the log deletes absent edges —
        # consumers treat it as a progress/sizing hint, like every
        # other cheap count
        return max(0, base + len(self.adds) - len(self.tombs))

    @property
    def num_edges(self) -> int:
        cheap = self.num_edges_cheap
        if cheap is not None:
            return cheap
        return sum(len(c) for c in self.chunks())

    @property
    def num_edges_upper_bound(self) -> Optional[int]:
        base = self.base.num_edges_upper_bound
        if base is None:
            return None
        return base + len(self.adds)

    def clamp_chunk_edges(self, chunk_edges: int, parts: int = 1,
                          floor: int = 1024) -> int:
        from sheep_tpu.io.edgestream import EdgeStream

        return EdgeStream.clamp_chunk_edges.__get__(self)(
            chunk_edges, parts, floor)

    def content_fingerprint(self) -> str:
        import hashlib

        st = os.stat(self.path)
        blob = (f"{self.base_spec}|{st.st_size}|{st.st_mtime_ns}|"
                f"{self.epoch}")
        return hashlib.sha1(blob.encode()).hexdigest()

    def __enter__(self) -> "DeltaLogStream":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def anchor_chunks(self, chunk_edges: int,
                      start_chunk: int = 0) -> Iterator[np.ndarray]:
        """The ORDER ANCHOR: the base segment's chunks only — what the
        degrees pass of a delta-log build streams (anchored-order
        semantics; the n it scatters into is this stream's full
        vertex space, so vertices the log introduced rank as
        degree-0)."""
        yield from self.base.chunks(chunk_edges, start_chunk=start_chunk)

    def anchor_stream(self):
        """The base stream object (device-stream bases stay device
        streams for the anchor pass)."""
        return self.base

    def chunks(self, chunk_edges: int = 1 << 22, shard: int = 0,
               num_shards: int = 1, start_chunk: int = 0,
               byte_range: bool = False) -> Iterator[np.ndarray]:
        if num_shards != 1:
            raise NotImplementedError(
                "delta: inputs stream single-shard (multi-host meshes "
                "reject anchored streams; single-process multi-device "
                "runs ingest the one shard lockstep)")
        idx = 0
        for c in filter_tombstones(
                self.base.chunks(chunk_edges), self.tombs):
            if idx >= start_chunk:
                yield c
            idx += 1
        for off in range(0, len(self.adds), chunk_edges):
            if idx >= start_chunk:
                yield self.adds[off: off + chunk_edges]
            idx += 1

    def read_all(self) -> np.ndarray:
        out = list(self.chunks())
        if not out:
            return np.zeros((0, 2), dtype=np.int64)
        return np.concatenate(out, axis=0)


def open_delta(spec_rest: str,
               n_vertices: Optional[int] = None) -> DeltaLogStream:
    """``delta:LOG[@EPOCH]`` -> DeltaLogStream (the one-shot surviving
    multiset up to EPOCH, default all)."""
    path, sep, ep = spec_rest.rpartition("@")
    up_to = None
    if sep and ep.isdigit():
        up_to = int(ep)
    else:
        path = spec_rest
    if not path or not os.path.exists(path):
        raise ValueError(f"delta log {path!r} does not exist "
                         f"(want delta:LOG[@EPOCH])")
    return DeltaLogStream(path, up_to=up_to, n_vertices=n_vertices)
