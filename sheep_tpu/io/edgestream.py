"""EdgeStream — chunked, shardable edge-list ingestion (SURVEY.md §2 #1).

The trillion-edge contract [NORTH-STAR]: never materialize the full graph.
Edges are read in fixed-size chunks; chunks are sharded across workers by
round-robin on chunk index, so every worker touches a disjoint byte range
and the union of shards is exactly the file. Device memory stays
O(V + chunk), not O(E) — the edge stream is this workload's "long sequence"
(SURVEY.md §5), scaled by chunking + sharding rather than ring attention.

Binary files shard by byte offset (seek is free); text files stream
line-blocks.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from sheep_tpu.io import formats

DEFAULT_CHUNK_EDGES = 1 << 22  # 4M edges/chunk = 64 MB of u64 pairs


class EdgeStream:
    """A re-openable stream of (chunk_size, 2) int64 edge arrays."""

    def __init__(
        self,
        path: Optional[str] = None,
        fmt: Optional[str] = None,
        edges: Optional[np.ndarray] = None,
        n_vertices: Optional[int] = None,
    ):
        if (path is None) == (edges is None):
            raise ValueError("exactly one of path / edges required")
        self.path = path
        self._edges = None if edges is None else np.asarray(edges, dtype=np.int64)
        self.fmt = fmt or (formats.detect_format(path) if path else "memory")
        self._n_vertices = n_vertices
        self._n_edges: Optional[int] = None
        if self._edges is not None:
            self._n_edges = len(self._edges)

    # -- constructors ------------------------------------------------------
    @classmethod
    def open(cls, path: str, fmt: Optional[str] = None, n_vertices: Optional[int] = None) -> "EdgeStream":
        return cls(path=path, fmt=fmt, n_vertices=n_vertices)

    @classmethod
    def from_array(cls, edges: np.ndarray, n_vertices: Optional[int] = None) -> "EdgeStream":
        return cls(edges=edges, n_vertices=n_vertices)

    # -- context manager (no persistent fd held between passes) ------------
    def __enter__(self) -> "EdgeStream":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    # -- metadata ----------------------------------------------------------
    @property
    def num_edges(self) -> int:
        if self._n_edges is None:
            if self.fmt == "bin32":
                self._n_edges = os.path.getsize(self.path) // 8
            elif self.fmt == "bin64":
                self._n_edges = os.path.getsize(self.path) // 16
            else:  # text: one counting pass
                n = 0
                for chunk in self.chunks():
                    n += len(chunk)
                self._n_edges = n
        return self._n_edges

    @property
    def num_edges_cheap(self) -> Optional[int]:
        """num_edges when it costs O(1) (binary/memory formats or already
        counted); None when computing it would require a file pass."""
        if self._n_edges is not None or self.fmt in ("bin32", "bin64"):
            return self.num_edges
        return None

    @property
    def num_vertices(self) -> int:
        """max vertex id + 1; computed by a streaming pass if not provided."""
        if self._n_vertices is None:
            m = -1
            for chunk in self.chunks():
                if len(chunk):
                    m = max(m, int(chunk.max()))
            self._n_vertices = m + 1
        return self._n_vertices

    # -- streaming ---------------------------------------------------------
    def chunks(
        self,
        chunk_edges: int = DEFAULT_CHUNK_EDGES,
        shard: int = 0,
        num_shards: int = 1,
        start_chunk: int = 0,
    ) -> Iterator[np.ndarray]:
        """Yield (<=chunk_edges, 2) int64 arrays.

        ``shard``/``num_shards`` round-robins chunks across workers;
        ``start_chunk`` skips already-processed *global* chunk indices
        (checkpoint/resume support, SURVEY.md §5).
        """
        if not (0 <= shard < num_shards):
            raise ValueError(f"bad shard {shard}/{num_shards}")
        if self._edges is not None:
            yield from self._chunks_memory(chunk_edges, shard, num_shards, start_chunk)
        elif self.fmt in ("bin32", "bin64"):
            yield from self._chunks_binary(chunk_edges, shard, num_shards, start_chunk)
        else:
            yield from self._chunks_text(chunk_edges, shard, num_shards, start_chunk)

    def _owns(self, idx: int, shard: int, num_shards: int, start_chunk: int) -> bool:
        return idx >= start_chunk and idx % num_shards == shard

    def _chunks_memory(self, chunk_edges, shard, num_shards, start_chunk):
        e = self._edges
        for idx, off in enumerate(range(0, len(e), chunk_edges)):
            if self._owns(idx, shard, num_shards, start_chunk):
                yield e[off : off + chunk_edges]

    def _chunks_binary(self, chunk_edges, shard, num_shards, start_chunk):
        dtype = np.dtype("<u4") if self.fmt == "bin32" else np.dtype("<u8")
        pair_bytes = 2 * dtype.itemsize
        total = self.num_edges
        with open(self.path, "rb") as f:
            for idx, off in enumerate(range(0, total, chunk_edges)):
                if not self._owns(idx, shard, num_shards, start_chunk):
                    continue
                count = min(chunk_edges, total - off)
                f.seek(off * pair_bytes)
                flat = np.fromfile(f, dtype=dtype, count=2 * count)
                yield flat.reshape(-1, 2).astype(np.int64, copy=False)

    def _chunks_text(self, chunk_edges, shard, num_shards, start_chunk):
        try:
            from sheep_tpu.core import native

            if native.available():
                yield from self._chunks_text_native(
                    native, chunk_edges, shard, num_shards, start_chunk)
                return
        except Exception:
            pass
        yield from self._chunks_text_python(chunk_edges, shard, num_shards, start_chunk)

    def _chunks_text_native(self, native, chunk_edges, shard, num_shards, start_chunk):
        """Block-wise parse via the C parser (~10x the Python loop). Malformed
        lines are skipped — the same policy as the Python path."""
        pend: list = []
        pend_n = 0
        idx = 0
        tail = b""
        with open(self.path, "rb") as f:
            while True:
                block = f.read(1 << 24)
                data = tail + block
                if not data:
                    break
                if block:
                    edges, consumed = native.parse_text(data)
                    tail = data[consumed:]
                else:  # final partial line (no trailing newline)
                    edges, _ = native.parse_text(data + b"\n")
                    tail = b""
                pend.append(edges)
                pend_n += len(edges)
                while pend_n >= chunk_edges:
                    cat = np.concatenate(pend)
                    if self._owns(idx, shard, num_shards, start_chunk):
                        yield cat[:chunk_edges]
                    pend = [cat[chunk_edges:]]
                    pend_n = len(pend[0])
                    idx += 1
                if not block:
                    break
        rest = np.concatenate(pend) if pend else np.zeros((0, 2), np.int64)
        if len(rest) and self._owns(idx, shard, num_shards, start_chunk):
            yield rest

    def _chunks_text_python(self, chunk_edges, shard, num_shards, start_chunk):
        from sheep_tpu.io.formats import parse_text_line

        buf: list = []
        idx = 0
        with open(self.path, "r") as f:
            for line in f:
                pair = parse_text_line(line)
                if pair is None:
                    continue
                buf.append(pair)
                if len(buf) == chunk_edges:
                    if self._owns(idx, shard, num_shards, start_chunk):
                        yield np.asarray(buf, dtype=np.int64)
                    buf = []
                    idx += 1
        if buf and self._owns(idx, shard, num_shards, start_chunk):
            yield np.asarray(buf, dtype=np.int64)

    def read_all(self) -> np.ndarray:
        """Materialize (tests / small graphs only)."""
        if self._edges is not None:
            return self._edges
        out = list(self.chunks())
        if not out:
            return np.zeros((0, 2), dtype=np.int64)
        return np.concatenate(out, axis=0)
