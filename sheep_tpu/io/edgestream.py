"""EdgeStream — chunked, shardable edge-list ingestion (SURVEY.md §2 #1).

The trillion-edge contract [NORTH-STAR]: never materialize the full graph.
Edges are read in fixed-size chunks; chunks are sharded across workers by
round-robin on chunk index, so every worker touches a disjoint byte range
and the union of shards is exactly the file. Device memory stays
O(V + chunk), not O(E) — the edge stream is this workload's "long sequence"
(SURVEY.md §5), scaled by chunking + sharding rather than ring attention.

Binary files shard by byte offset (seek is free); text files stream
line-blocks.

Fault tolerance (ISSUE 9): physical reads run under a bounded retry
policy (utils/retry.py — transient OSErrors back off and re-read, so
one NFS blip doesn't kill an hours-long build), and binary streams are
VALIDATED: a torn pair (file size not a multiple of the record size) or
a short read (the file shrank under a live stream — "mid-stream EOF")
is never silently folded into the forest. What happens instead is the
``SHEEP_IO_POLICY`` contract:

    strict      (default) raise :class:`CorruptStreamError` — the run
                dies with a diagnosis instead of building a partition
                of a graph that isn't the one on disk
    quarantine  drop the torn tail / the missing remainder, emit a
                ``chunk_quarantined`` trace event + stderr warning, and
                continue over the intact prefix — the documented
                degraded mode the chaos soak accepts

Either way the result is quarantine-or-raise, never a wrong forest
built from garbage bytes (tests/test_edgestream.py fuzz cases).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from sheep_tpu.io import formats

DEFAULT_CHUNK_EDGES = 1 << 22  # 4M edges/chunk = 64 MB of u64 pairs

IO_POLICY_ENV = "SHEEP_IO_POLICY"


class CorruptStreamError(ValueError):
    """Torn/corrupt/shrunken input detected under the strict IO policy."""


def _io_policy() -> str:
    v = os.environ.get(IO_POLICY_ENV, "strict") or "strict"
    if v not in ("strict", "quarantine"):
        raise ValueError(f"bad {IO_POLICY_ENV}={v!r}; "
                         f"want 'strict' or 'quarantine'")
    return v


def _quarantine_or_raise(msg: str, **fields) -> None:
    """Apply the IO policy to a detected corruption: raise (strict) or
    warn + trace-event and let the caller continue (quarantine)."""
    if _io_policy() == "strict":
        raise CorruptStreamError(
            msg + " (set SHEEP_IO_POLICY=quarantine to drop the "
                  "damaged bytes and continue)")
    import sys

    print(f"edgestream quarantine: {msg}", file=sys.stderr)
    from sheep_tpu import obs

    obs.event("chunk_quarantined", message=msg, **fields)


def _read_retry_policy():
    """Read-side retry policy: same knobs as the device-side one, but a
    fresh budget per stream pass (a pass that survives three separate
    blips over a billion edges is healthy, not dying)."""
    from sheep_tpu.utils.retry import RetryPolicy

    return RetryPolicy()


def _retrying(policy, fn, where: str):
    """Run a physical read under the bounded TRANSIENT retry budget.
    Non-transient errors (and an exhausted budget) propagate."""
    from sheep_tpu.utils.retry import TRANSIENT, classify

    while True:
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classified below
            if classify(exc) != TRANSIENT or not policy.admit(TRANSIENT):
                raise
            policy.backoff(TRANSIENT, exc, where=where)


class EdgeStream:
    """A re-openable stream of (chunk_size, 2) int64 edge arrays."""

    def __init__(
        self,
        path: Optional[str] = None,
        fmt: Optional[str] = None,
        edges: Optional[np.ndarray] = None,
        n_vertices: Optional[int] = None,
        factory=None,
        num_edges: Optional[int] = None,
    ):
        if sum(x is not None for x in (path, edges, factory)) != 1:
            raise ValueError("exactly one of path / edges / factory required")
        self.path = path
        self._edges = None if edges is None else np.asarray(edges, dtype=np.int64)
        self._factory = factory
        self.fmt = fmt or (formats.detect_format(path) if path
                           else ("generator" if factory else "memory"))
        self._n_vertices = n_vertices
        self._n_edges: Optional[int] = num_edges
        if self._edges is not None:
            self._n_edges = len(self._edges)

    # -- constructors ------------------------------------------------------
    @classmethod
    def open(cls, path: str, fmt: Optional[str] = None, n_vertices: Optional[int] = None) -> "EdgeStream":
        return cls(path=path, fmt=fmt, n_vertices=n_vertices)

    @classmethod
    def from_array(cls, edges: np.ndarray, n_vertices: Optional[int] = None) -> "EdgeStream":
        return cls(edges=edges, n_vertices=n_vertices)

    @classmethod
    def from_generator(cls, factory, n_vertices: Optional[int] = None,
                       num_edges: Optional[int] = None) -> "EdgeStream":
        """Stream from a re-openable chunk generator (trillion-edge soak
        path: ``generators.rmat_stream`` never materializes the graph).

        ``factory()`` must return a FRESH iterator of (c, 2) int arrays
        each call — the pipeline makes multiple passes (degrees, build,
        score), and checkpoint resume re-opens mid-stream. rmat_stream is
        seeded per chunk, so replaying is deterministic and cheap.
        """
        return cls(factory=factory, n_vertices=n_vertices, num_edges=num_edges)

    # -- context manager (no persistent fd held between passes) ------------
    def __enter__(self) -> "EdgeStream":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    # -- metadata ----------------------------------------------------------
    @property
    def num_edges(self) -> int:
        if self._n_edges is None:
            if self.fmt == "bin32":
                self._n_edges = os.path.getsize(self.path) // 8
            elif self.fmt == "bin64":
                self._n_edges = os.path.getsize(self.path) // 16
            elif self.fmt == "csr":
                from sheep_tpu.io import csr as csr_mod

                self._n_edges = csr_mod.read_header(self.path).n_edges
            else:  # text/generator: one counting pass
                n = 0
                for chunk in self.chunks():
                    n += len(chunk)
                self._n_edges = n
        return self._n_edges

    @property
    def num_edges_cheap(self) -> Optional[int]:
        """num_edges when it costs O(1) (binary/memory formats or already
        counted); None when computing it would require a file pass."""
        if self._n_edges is not None or self.fmt in ("bin32", "bin64", "csr"):
            return self.num_edges
        return None

    @property
    def num_edges_upper_bound(self) -> Optional[int]:
        """O(1) upper bound on num_edges: exact where cheap, else the
        text-format floor of >= 4 bytes per edge line ("0 1\\n"). Used to
        right-size chunk buffers without paying a counting pass; None
        only for unsized generator streams."""
        cheap = self.num_edges_cheap
        if cheap is not None:
            return cheap
        if self.fmt == "text-gz":
            # the >=4-bytes-per-line floor holds for the DECOMPRESSED
            # text; on the compressed size it would not be an upper
            # bound at all
            return None
        if self.path is not None:
            # +1: the last line may lack its trailing newline
            return (os.path.getsize(self.path) + 1) // 4
        return None

    def clamp_chunk_edges(self, chunk_edges: int, parts: int = 1,
                          floor: int = 1024) -> int:
        """Shrink ``chunk_edges`` for small streams using the O(1) size
        bound (shared by the single-device and sharded backends so their
        chunk sizing — and checkpoint fingerprints — cannot diverge).
        ``parts`` divides the bound across devices."""
        bound = self.num_edges_upper_bound
        if bound is None:
            return chunk_edges
        return min(chunk_edges, max(floor, -(-bound // parts)))

    @property
    def num_vertices(self) -> int:
        """max vertex id + 1; O(1) from the CSR header, else a streaming
        pass if not provided."""
        if self._n_vertices is None:
            if self.fmt == "csr":
                from sheep_tpu.io import csr as csr_mod

                self._n_vertices = csr_mod.read_header(self.path).n_vertices
                return self._n_vertices
            m = -1
            for chunk in self.chunks():
                if len(chunk):
                    m = max(m, int(chunk.max()))
            self._n_vertices = m + 1
        return self._n_vertices

    # -- streaming ---------------------------------------------------------
    def chunks(
        self,
        chunk_edges: int = DEFAULT_CHUNK_EDGES,
        shard: int = 0,
        num_shards: int = 1,
        start_chunk: int = 0,
        byte_range: bool = False,
    ) -> Iterator[np.ndarray]:
        """Yield (<=chunk_edges, 2) int64 arrays.

        ``shard``/``num_shards`` round-robins chunks across workers;
        ``start_chunk`` skips already-processed *global* chunk indices
        (checkpoint/resume support, SURVEY.md §5).

        ``byte_range`` (text files only): instead of every worker parsing
        the whole file and keeping 1/P of the chunks (O(P x file) total
        parse work), worker p parses only the byte span
        [size*p/P, size*(p+1)/P) with newline-boundary fixup — O(file)
        total. Its local chunk j carries global index j*P + p, so
        ``start_chunk`` resume semantics are unchanged. Binary/memory
        formats ignore the flag (seeking already costs O(1/P) each).
        """
        if not (0 <= shard < num_shards):
            raise ValueError(f"bad shard {shard}/{num_shards}")
        if self._factory is not None:
            yield from self._chunks_generator(chunk_edges, shard, num_shards, start_chunk)
        elif self._edges is not None:
            yield from self._chunks_memory(chunk_edges, shard, num_shards, start_chunk)
        elif self.fmt in ("bin32", "bin64"):
            yield from self._chunks_binary(chunk_edges, shard, num_shards, start_chunk)
        elif self.fmt == "csr":
            yield from self._chunks_csr(chunk_edges, shard, num_shards, start_chunk)
        elif self.fmt == "text-gz":
            # a gzip member is one sequential stream: no byte-range
            # sharding, no seeks — every worker decompresses and keeps
            # its round-robin chunks (fine for ingest-once workflows;
            # recompress to .bin32/.csr for the multi-pass pipeline)
            yield from self._chunks_text_gz(chunk_edges, shard, num_shards, start_chunk)
        elif byte_range:
            yield from self._chunks_text_span(chunk_edges, shard, num_shards, start_chunk)
        else:
            yield from self._chunks_text(chunk_edges, shard, num_shards, start_chunk)

    def count_edges_in_span(self, shard: int, num_shards: int) -> int:
        """Edges in this worker's byte span (one O(file/P) parse, cached).
        Used by the sharded pipeline to agree on lockstep batch counts."""
        key = (shard, num_shards)
        if not hasattr(self, "_span_counts"):
            self._span_counts: dict = {}
        if key not in self._span_counts:
            self._span_counts[key] = sum(
                len(c) for c in self.chunks(
                    DEFAULT_CHUNK_EDGES, shard=shard, num_shards=num_shards,
                    byte_range=True))
        return self._span_counts[key]

    def _owns(self, idx: int, shard: int, num_shards: int, start_chunk: int) -> bool:
        return idx >= start_chunk and idx % num_shards == shard

    @staticmethod
    def _regroup(blocks, chunk_edges, own):
        """Accumulate variable-size (c, 2) edge blocks into fixed-size
        chunks; ``own(idx)`` filters by sequential chunk index. Shared by
        the generator, native-text and byte-span paths so ownership/
        boundary semantics cannot diverge between them."""
        pend: list = []
        pend_n = 0
        idx = 0
        for block in blocks:
            block = np.asarray(block, dtype=np.int64).reshape(-1, 2)
            pend.append(block)
            pend_n += len(block)
            while pend_n >= chunk_edges:
                cat = np.concatenate(pend)
                if own(idx):
                    yield cat[:chunk_edges]
                pend = [cat[chunk_edges:]]
                pend_n = len(pend[0])
                idx += 1
        rest = np.concatenate(pend) if pend else np.zeros((0, 2), np.int64)
        if len(rest) and own(idx):
            yield rest

    def _chunks_generator(self, chunk_edges, shard, num_shards, start_chunk):
        yield from self._regroup(
            self._factory(), chunk_edges,
            lambda idx: self._owns(idx, shard, num_shards, start_chunk))

    def _chunks_memory(self, chunk_edges, shard, num_shards, start_chunk):
        e = self._edges
        for idx, off in enumerate(range(0, len(e), chunk_edges)):
            if self._owns(idx, shard, num_shards, start_chunk):
                yield e[off : off + chunk_edges]

    def _chunks_binary(self, chunk_edges, shard, num_shards, start_chunk):
        from sheep_tpu.utils import fault

        dtype = np.dtype("<u4") if self.fmt == "bin32" else np.dtype("<u8")
        pair_bytes = 2 * dtype.itemsize
        total = self.num_edges
        policy = _read_retry_policy()
        size = os.path.getsize(self.path)
        if size % pair_bytes:
            # torn trailing pair: num_edges floors it away, so without
            # this check the damage would be SILENT truncation
            _quarantine_or_raise(
                f"{self.path}: {size} bytes is not a multiple of the "
                f"{pair_bytes}-byte edge record ({size % pair_bytes} "
                f"torn trailing bytes)",
                path=self.path, torn_bytes=size % pair_bytes)
        with _retrying(policy, lambda: open(self.path, "rb"),
                       f"open {self.path}") as f:
            reads = 0
            for idx, off in enumerate(range(0, total, chunk_edges)):
                if not self._owns(idx, shard, num_shards, start_chunk):
                    continue
                count = min(chunk_edges, total - off)
                reads += 1

                def _read(off=off, count=count, reads=reads):
                    fault.maybe_fail("read", reads, kinds=("read",))
                    f.seek(off * pair_bytes)
                    return np.fromfile(f, dtype=dtype, count=2 * count)

                flat = _retrying(policy, _read,
                                 f"read {self.path} chunk {idx}")
                if len(flat) != 2 * count:
                    # mid-stream EOF: the file shrank under us (or the
                    # metadata lied). Never fold a half-read: keep the
                    # intact pair prefix under quarantine, else raise.
                    _quarantine_or_raise(
                        f"{self.path}: short read at chunk {idx} "
                        f"(wanted {count} edges at offset "
                        f"{off * pair_bytes}, got {len(flat) // 2} "
                        f"intact pairs) — stream truncated mid-pass",
                        path=self.path, chunk=idx,
                        expected=int(count), got=int(len(flat) // 2))
                    flat = flat[: 2 * (len(flat) // 2)]
                    if len(flat):
                        yield flat.reshape(-1, 2).astype(np.int64,
                                                         copy=False)
                    return  # everything past the tear is gone
                yield flat.reshape(-1, 2).astype(np.int64, copy=False)

    def _chunks_csr(self, chunk_edges, shard, num_shards, start_chunk):
        """O(log V) seek per chunk via the mmapped indptr (csr.py
        edge_slice); ownership/indexing identical to _chunks_binary."""
        from sheep_tpu.io import csr as csr_mod

        g = csr_mod.CsrGraph(self.path)
        try:
            total = g.n_edges
            for idx, off in enumerate(range(0, total, chunk_edges)):
                if not self._owns(idx, shard, num_shards, start_chunk):
                    continue
                yield g.edge_slice(off, min(off + chunk_edges, total))
        finally:
            g.close()

    def _chunks_text_gz(self, chunk_edges, shard, num_shards, start_chunk):
        """Streamed gzip text: decompress 16 MB blocks, parse with the
        shared block parser (native when built), regroup with the common
        ownership semantics."""
        import gzip

        yield from self._regroup(
            self._text_blocks(lambda: gzip.open(self.path, "rb"),
                              self._block_parser()),
            chunk_edges,
            lambda idx: self._owns(idx, shard, num_shards, start_chunk))

    def _chunks_text(self, chunk_edges, shard, num_shards, start_chunk):
        try:
            from sheep_tpu.core import native

            if native.available():
                yield from self._chunks_text_native(
                    native, chunk_edges, shard, num_shards, start_chunk)
                return
        except Exception:
            pass
        yield from self._chunks_text_python(chunk_edges, shard, num_shards, start_chunk)

    @staticmethod
    def _text_blocks(open_fn, parse):
        """Block-wise text parse shared by the plain and gzip paths: one
        copy of the subtle partial-line boundary handling (tail carry,
        consumed offset, EOF-without-trailing-newline). ``open_fn()``
        must return a binary file-like; ``parse(bytes)`` -> (edges,
        consumed) is the shared block-parser contract. Physical reads
        run under the bounded transient-retry policy (module
        docstring), with EXPLICIT repositioning before every read: a
        failed buffered/gzip ``read`` may already have consumed raw
        bytes (CPython discards data buffered by a mid-call error), so
        a blind re-read would silently skip them — the seek to the
        last consumed logical offset makes the retry sound (for
        GzipFile a backward seek rewinds and re-decompresses, slow but
        only on an actual retry). A non-seekable stream cannot
        reposition, so its mid-stream reads are NOT retried — the
        error propagates rather than risking a silent gap."""
        from sheep_tpu.utils import fault

        tail = b""
        policy = _read_retry_policy()
        nblocks = 0
        pos = 0  # logical (decompressed) offset of consumed bytes
        with _retrying(policy, open_fn, "open text stream") as f:
            try:
                seekable = bool(f.seekable())
            except Exception:
                seekable = False
            while True:
                nblocks += 1

                def _read(nblocks=nblocks, pos=pos):
                    fault.maybe_fail("read", nblocks, kinds=("read",))
                    if seekable:
                        f.seek(pos)
                    return f.read(1 << 24)

                if seekable:
                    block = _retrying(policy, _read,
                                      f"read text block {nblocks}")
                else:
                    block = _read()
                pos += len(block)
                data = tail + block
                if not data:
                    return
                if block:
                    edges, consumed = parse(data)
                    tail = data[consumed:]
                else:  # final partial line (no trailing newline)
                    edges, _ = parse(data + b"\n")
                    tail = b""
                yield edges
                if not block:
                    return

    def _chunks_text_native(self, native, chunk_edges, shard, num_shards, start_chunk):
        """Block-wise parse via the C parser (~10x the Python loop). Malformed
        lines are skipped — the same policy as the Python path."""
        yield from self._regroup(
            self._text_blocks(lambda: open(self.path, "rb"),
                              native.parse_text),
            chunk_edges,
            lambda idx: self._owns(idx, shard, num_shards, start_chunk))

    def _chunks_text_span(self, chunk_edges, shard, num_shards, start_chunk):
        """Parse only this shard's byte span of a text file.

        Boundary rule: a line belongs to the span containing its FIRST
        byte. Entering mid-line (previous byte != newline) skips to the
        next line; a line straddling the span's end is finished past the
        boundary. Local chunk j is yielded iff its global index
        j*num_shards + shard passes the ``start_chunk`` filter.
        """
        size = os.path.getsize(self.path)
        start = size * shard // num_shards
        end = size * (shard + 1) // num_shards

        parse = self._block_parser()

        def spans():
            with open(self.path, "rb") as f:
                if start > 0:
                    f.seek(start - 1)
                    if f.read(1) != b"\n":
                        f.readline()  # tail of a line owned by the previous span
                tail = b""
                while f.tell() < end:
                    block = f.read(min(1 << 24, end - f.tell()))
                    if not block:
                        break
                    data = tail + block
                    edges, consumed = parse(data)
                    tail = data[consumed:]
                    if len(edges):
                        yield edges
                if tail:  # line straddling `end` (or EOF without newline)
                    data = tail + f.readline()
                    if not data.endswith(b"\n"):
                        data += b"\n"
                    edges, _ = parse(data)
                    if len(edges):
                        yield edges

        # local chunk j carries global index j * num_shards + shard
        yield from self._regroup(
            spans(), chunk_edges,
            lambda j: j * num_shards + shard >= start_chunk)

    @staticmethod
    def _block_parser():
        """Best block parser available: the native C parser, else the
        Python fallback — one dispatch shared by every text path."""
        try:
            from sheep_tpu.core import native

            if native.available():
                return native.parse_text
        except Exception:
            pass
        return EdgeStream._parse_block_python

    @staticmethod
    def _parse_block_python(data: bytes):
        """Python fallback for the native block parser: complete lines
        only; returns (edges, bytes_consumed)."""
        from sheep_tpu.io.formats import parse_text_line

        nl = data.rfind(b"\n")
        if nl < 0:
            return np.zeros((0, 2), np.int64), 0
        out = []
        for line in data[: nl + 1].decode("utf-8", "replace").splitlines():
            pair = parse_text_line(line)
            if pair is not None:
                out.append(pair)
        arr = (np.asarray(out, dtype=np.int64) if out
               else np.zeros((0, 2), np.int64))
        return arr, nl + 1

    def _chunks_text_python(self, chunk_edges, shard, num_shards, start_chunk):
        from sheep_tpu.io.formats import parse_text_line

        buf: list = []
        idx = 0
        with open(self.path, "r") as f:
            for line in f:
                pair = parse_text_line(line)
                if pair is None:
                    continue
                buf.append(pair)
                if len(buf) == chunk_edges:
                    if self._owns(idx, shard, num_shards, start_chunk):
                        yield np.asarray(buf, dtype=np.int64)
                    buf = []
                    idx += 1
        if buf and self._owns(idx, shard, num_shards, start_chunk):
            yield np.asarray(buf, dtype=np.int64)

    def read_all(self) -> np.ndarray:
        """Materialize (tests / small graphs only)."""
        if self._edges is not None:
            return self._edges
        out = list(self.chunks())
        if not out:
            return np.zeros((0, 2), dtype=np.int64)
        return np.concatenate(out, axis=0)


def open_input(spec: str, n_vertices: Optional[int] = None):
    """Open a CLI/API ``--input`` value: a graph file path, or a synthetic
    stream spec (eval config 5 is RMAT-30 — a trillion-edge-class synthetic
    needs no file):

    - ``rmat-hash:SCALE[:EF[:SEED]]`` — counter-based R-MAT
      (:class:`~sheep_tpu.io.generators.RmatHashStream`): random-access
      chunks, generated ON DEVICE by the TPU backend, replay-free resume.
    - ``rmat:SCALE[:EF[:SEED]]`` — the PCG replay generator
      (:func:`~sheep_tpu.io.generators.rmat_stream`) behind a generator
      EdgeStream (matches the soak artifacts generated with it).
    - ``sbm-hash:SCALE:BLOCKS:POUT[:EF[:SEED]]`` — counter-based
      planted partition (:class:`~sheep_tpu.io.generators.SbmHashStream`):
      BLOCKS power-of-two ground-truth communities, inter-block edge
      fraction POUT (a float) — known-optimal-cut quality evaluation at
      arbitrary scale.
    - ``plsbm-hash:SCALE:BLOCKS:POUT[:EF[:SEED]]`` — the planted
      partition with POWER-LAW within-block degrees
      (:class:`~sheep_tpu.io.generators.PowerlawSbmHashStream`).
    - ``bipartite-hash:SCALE:BLOCKS:POUT[:EF[:SEED]]`` — planted
      BIPARTITE communities, every edge crossing the two vertex halves
      (:class:`~sheep_tpu.io.generators.BipartiteHashStream`).
    - ``nearclique-hash:SCALE:CLIQUE_BITS:POUT[:EF[:SEED]]`` — dense
      near-clique blocks of 2**CLIQUE_BITS vertices
      (:class:`~sheep_tpu.io.generators.NearCliqueStream`).

    - ``delta:LOG[@EPOCH]`` — a mutating graph: the surviving edge
      multiset of a base input plus an append-log of epoch-stamped
      add/tombstone records (:mod:`sheep_tpu.io.deltalog`), capped at
      EPOCH when given. Delta-log builds use the ANCHORED elimination
      order (base-segment degrees), the contract that makes the
      incremental path (:mod:`sheep_tpu.incremental`) bit-identical
      to this one-shot build.

    Anything else is treated as a path (format by extension). A
    user-supplied ``n_vertices`` must not contradict a synthetic spec's
    2**SCALE vertex space.
    """
    spec = os.fspath(spec)  # pathlib.Path inputs flow through unchanged
    kind, _, rest = spec.partition(":")
    if kind == "delta" and rest:
        from sheep_tpu.io.deltalog import open_delta

        return open_delta(rest, n_vertices=n_vertices)
    # the planted-structure family shares one SCALE:ARG:POUT[:EF[:SEED]]
    # grammar; ARG is the second structural knob of each class
    planted = {"sbm-hash": ("BLOCKS", "SbmHashStream"),
               "plsbm-hash": ("BLOCKS", "PowerlawSbmHashStream"),
               "bipartite-hash": ("BLOCKS", "BipartiteHashStream"),
               "nearclique-hash": ("CLIQUE_BITS", "NearCliqueStream")}
    if kind in planted and rest:
        from sheep_tpu.io import generators

        argname, clsname = planted[kind]
        shape = f"{kind}:SCALE:{argname}:POUT[:EF[:SEED]]"
        parts = rest.split(":")
        if not 3 <= len(parts) <= 5:
            raise ValueError(
                f"bad synthetic input spec {spec!r}; want {shape}")
        try:
            scale, arg = int(parts[0]), int(parts[1])
            p_out = float(parts[2])
            ef = int(parts[3]) if len(parts) > 3 else 16
            seed = int(parts[4]) if len(parts) > 4 else 0
        except ValueError:
            raise ValueError(
                f"bad synthetic input spec {spec!r}; want {shape} "
                f"(POUT a float, the rest integers)")
        if not (1 <= scale <= 31) or ef < 1:
            raise ValueError(f"bad synthetic input spec {spec!r}: "
                             f"need 1 <= SCALE <= 31 and EF >= 1")
        if n_vertices is not None and n_vertices != 1 << scale:
            raise ValueError(
                f"--num-vertices {n_vertices} contradicts {spec!r} "
                f"(2**{scale} = {1 << scale} vertices)")
        # blocks/clique_bits/p_out range checks live in each class
        return getattr(generators, clsname)(scale, arg, p_out,
                                            edge_factor=ef, seed=seed)
    if kind in ("rmat-hash", "rmat") and rest:
        from sheep_tpu.io import generators

        parts = rest.split(":")
        if len(parts) > 3:
            raise ValueError(
                f"bad synthetic input spec {spec!r}; want "
                f"{kind}:SCALE[:EF[:SEED]] (got {len(parts)} fields)")
        try:
            scale = int(parts[0])
            ef = int(parts[1]) if len(parts) > 1 else 16
            seed = int(parts[2]) if len(parts) > 2 else 0
        except ValueError:
            raise ValueError(
                f"bad synthetic input spec {spec!r}; want "
                f"{kind}:SCALE[:EF[:SEED]] with integer fields")
        # rmat-hash accumulates vertex bits in uint32 (scale > 32 would
        # silently truncate); the int64 PCG path goes further
        max_scale = 32 if kind == "rmat-hash" else 40
        if not (1 <= scale <= max_scale) or ef < 1:
            raise ValueError(f"bad synthetic input spec {spec!r}: "
                             f"need 1 <= SCALE <= {max_scale} and EF >= 1")
        if n_vertices is not None and n_vertices != 1 << scale:
            raise ValueError(
                f"--num-vertices {n_vertices} contradicts {spec!r} "
                f"(2**{scale} = {1 << scale} vertices)")
        if kind == "rmat-hash":
            return generators.RmatHashStream(scale, ef, seed=seed)
        return EdgeStream.from_generator(
            lambda: generators.rmat_stream(scale, ef, seed=seed),
            n_vertices=1 << scale, num_edges=ef << scale)
    return EdgeStream.open(spec, n_vertices=n_vertices)
