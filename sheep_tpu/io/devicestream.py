"""Device-resident stream synthesis — the ``DeviceStream`` protocol
(ISSUE 12 tentpole, leg a).

VERDICT r5 measured the tunneled build at ~70% host-interaction tax:
every chunk of a *synthetic* stream still paid a host generate → pad →
``jnp.asarray`` H2D upload before the device could fold it, even though
the counter-hash generators (io/generators.py) can compute any edge
range directly ON DEVICE, bit-identically to the host twin. This module
makes that capability a first-class input protocol instead of an ad-hoc
attribute probe: a :class:`DeviceStream` materializes each padded
``(C, 2)`` int32 chunk in accelerator memory, so a build over one pays
**zero host bytes per chunk** — no host generation, no H2D transfer, no
staging ring. The dispatch drivers (tpu backend, sharded pipeline, bigv
pipeline) and the served engine all recognize the protocol through
:func:`is_device_stream`.

Contract (what every implementation must hold):

- ``device_chunk(idx, chunk_edges, n)`` returns the ``(chunk_edges, 2)``
  int32 device array for GLOBAL chunk ``idx``, rows past the real edge
  count holding the sentinel vertex ``n`` — **bit-identical** to
  ``pad_chunk(host_chunk_idx, chunk_edges, n)`` of the same stream's
  host chunks, so cross-backend/oracle equality holds by construction
  (the fixpoint-uniqueness argument needs identical constraint
  multisets, and checkpoint fingerprints hash the host twin).
- ``num_device_chunks(chunk_edges)`` returns the total chunk count;
  ``device_chunk`` past it yields an all-sentinel (inert) chunk, which
  is what lets lockstep multi-device batch iteration pad stragglers
  without a host round-trip.
- Chunk access is RANDOM (any index independently), which keeps
  checkpoint resume, round-robin sharding and the shared chunk cache's
  prefix semantics exact rather than replay-based.

Host-format streams (files, in-memory arrays, replay generators) are
not device streams; they take the staged H2D ring
(:class:`sheep_tpu.utils.prefetch.H2DRing`) instead — leg (b) of the
same ingest overhaul.
"""

from __future__ import annotations


class DeviceStream:
    """Base / marker class for streams whose padded chunks materialize
    directly in device memory (see module docstring for the contract).
    Subclasses implement :meth:`device_chunk`; the EdgeStream surface
    (``chunks``/``num_vertices``/...) comes from the concrete stream
    class (e.g. ``io.generators._CounterHashStream``)."""

    def device_chunk(self, idx: int, chunk_edges: int, n: int):
        """Padded ``(chunk_edges, 2)`` int32 DEVICE chunk for global
        chunk ``idx`` (sentinel ``n`` past the real edge count)."""
        raise NotImplementedError

    def device_chunk_on(self, device, idx: int, chunk_edges: int, n: int):
        """:meth:`device_chunk` placed on a specific ``device`` — the
        multi-device drivers' placement hook. Synthesis runs on the
        default device and moves device-to-device (ICI on a real mesh);
        still zero host bytes."""
        import jax

        return jax.device_put(self.device_chunk(idx, chunk_edges, n),
                              device)


def is_device_stream(stream) -> bool:
    """True when ``stream`` can synthesize padded chunks on device:
    a :class:`DeviceStream`, or any object with a callable
    ``device_chunk`` (duck-typed third-party streams keep working)."""
    return isinstance(stream, DeviceStream) or \
        callable(getattr(stream, "device_chunk", None))


def note_device_chunks(stats, count: int = 1) -> None:
    """Account ``count`` device-synthesized chunks in a driver stats
    dict: bumps ``device_stream_chunks`` and pins ``h2d_staged_bytes``
    at its seeded value (0 unless a host-format pass also ran) — the
    trace-visible proof that the path paid zero per-chunk host staging
    bytes."""
    if stats is None:
        return
    stats.setdefault("h2d_staged_bytes", 0)
    stats["device_stream_chunks"] = \
        stats.get("device_stream_chunks", 0) + count
