"""Partitioner strategy plugin system (SURVEY.md §2 #10).

The reference selects an execution backend with ``--backend=...``
[NORTH-STAR]; this registry is the rebuild's equivalent. Backends register
themselves at import time; ``get_backend`` instantiates by name.
"""

from __future__ import annotations

import abc
from typing import Dict, List, Type

from sheep_tpu.types import PartitionResult

_REGISTRY: Dict[str, Type["Partitioner"]] = {}


class Partitioner(abc.ABC):
    """A partition strategy/backend: graph stream + k -> PartitionResult."""

    name: str = "?"

    @abc.abstractmethod
    def partition(self, stream, k: int, **opts) -> PartitionResult:
        """Partition the graph in *stream* into *k* parts."""

    # backends advertise capabilities the CLI/driver can query
    supports_streaming: bool = True
    supports_multidevice: bool = False


def register(cls: Type[Partitioner]) -> Type[Partitioner]:
    _REGISTRY[cls.name] = cls
    return cls


def list_backends() -> List[str]:
    return sorted(_REGISTRY)


def get_backend(name: str, **kw) -> Partitioner:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r}; available: {', '.join(list_backends())}"
        ) from None
    return cls(**kw)
