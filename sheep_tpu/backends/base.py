"""Partitioner strategy plugin system (SURVEY.md §2 #10).

The reference selects an execution backend with ``--backend=...``
[NORTH-STAR]; this registry is the rebuild's equivalent. Backends register
themselves at import time; ``get_backend`` instantiates by name.
"""

from __future__ import annotations

import abc
from typing import Dict, List, Type

from sheep_tpu.types import PartitionResult

_REGISTRY: Dict[str, Type["Partitioner"]] = {}


class Partitioner(abc.ABC):
    """A partition strategy/backend: graph stream + k -> PartitionResult."""

    name: str = "?"

    @abc.abstractmethod
    def partition(self, stream, k: int, **opts) -> PartitionResult:
        """Partition the graph in *stream* into *k* parts."""

    def partition_multi(self, stream, ks, weights: str = "unit",
                        comm_volume: bool = True, **opts):
        """One PartitionResult per k in ``ks`` — SHEEP's headline reuse
        property: the elimination tree is k-INDEPENDENT, so one
        degrees+build pays for every part count [PAPER]. Backends that
        honor ``keep_tree`` (pure/cpu/tpu) get extra k values for an
        O(V) re-split plus one scoring stream pass each; backends that
        don't fall back to independent full runs. Checkpoint/resume
        stays a single-k feature (pass checkpointer to partition())."""
        import sys
        import time

        import numpy as np

        ks = [int(k) for k in ks]
        if not ks:
            raise ValueError("ks must be non-empty")
        if opts.get("checkpointer") is not None:
            raise ValueError("partition_multi does not checkpoint; "
                             "run single-k partitions to checkpoint")
        opts.pop("keep_tree", None)  # we set it; a caller copy would
        # collide with the explicit kwarg below
        first = self.partition(stream, ks[0], weights=weights,
                               comm_volume=comm_volume, keep_tree=True,
                               **opts)
        out = [first]
        if len(ks) == 1:
            return out
        tree = first.tree
        if tree is None:  # backend doesn't expose its tree
            print(f"note: backend {self.name!r} does not expose its "
                  f"elimination tree; --k list runs {len(ks)} independent "
                  f"full partitions instead of one shared build",
                  file=sys.stderr)
            out += [self.partition(stream, k, weights=weights,
                                   comm_volume=comm_volume, **opts)
                    for k in ks[1:]]
            return out
        from sheep_tpu.ops.split import tree_split_host

        w = tree["deg"].astype(np.float64) if weights == "degree" else None
        split_s = {}
        assigns = {}
        for k in ks[1:]:
            t0 = time.perf_counter()
            assigns[k] = tree_split_host(tree["parent"], tree["pos"], k,
                                         weights=w,
                                         alpha=getattr(self, "alpha", 1.0))
            split_s[k] = time.perf_counter() - t0
        # ONE stream pass scores every extra assignment (the pass, not
        # the O(E) arithmetic, dominates on file/gz streams)
        t0 = time.perf_counter()
        scored = score_stream(
            stream, assigns,
            chunk_edges=getattr(self, "chunk_edges", 1 << 22),
            comm_volume=comm_volume, weights=w)
        score_s = time.perf_counter() - t0
        for k in ks[1:]:
            cut, total, balance, cv = scored[k]
            out.append(PartitionResult(
                assignment=assigns[k], k=k, edge_cut=cut,
                total_edges=total, cut_ratio=cut / max(total, 1),
                balance=balance, comm_volume=cv,
                phase_times={"split": split_s[k],
                             "score": score_s / len(ks[1:])},
                backend=self.name, tree=tree))
        return out

    # backends advertise capabilities the CLI/driver can query
    supports_streaming: bool = True
    supports_multidevice: bool = False
    # True when partition() takes checkpointer=/resume= (the chunk-level
    # recovery contract of utils/checkpoint); hierarchy consults this to
    # decide whether its level 0 gets a nested chunk-checkpoint domain
    # or level-boundary-only recovery
    supports_checkpoint: bool = False
    # True when the backend implements _fold_delta (fold a host delta
    # batch into a converged carried table) — the incremental-
    # repartitioning capability (ISSUE 15): partition_update applies
    # epoch-stamped add/tombstone batches in O(Δ) instead of an O(E)
    # rebuild, bit-identical to a one-shot build of the delta: input
    # under the anchored order (sheep_tpu/incremental.py)
    supports_incremental: bool = False

    def partition_update(self, state, adds=None, deletes=None, **opts):
        """Fold one delta epoch into a resident
        :class:`~sheep_tpu.incremental.PartitionState` (created by
        :func:`sheep_tpu.incremental.begin_incremental`): adds fold into the
        converged carried table via this backend's ``_fold_delta``
        hook, deletes tombstone (compaction rebuilds their subtrees —
        ``compact=`` forwards to the driver), the epoch advances, and
        ``score=True`` (default) returns the refreshed scored
        result(s). See :mod:`sheep_tpu.incremental` for the exactness
        contract."""
        if not self.supports_incremental:
            raise ValueError(
                f"backend {self.name!r} does not support incremental "
                f"updates (supports_incremental is False); use "
                f"pure/cpu/tpu")
        from sheep_tpu import incremental

        return incremental.apply_update(self, state, adds=adds,
                                        deletes=deletes, **opts)

    def _fold_delta(self, state, edges) -> None:
        raise NotImplementedError(
            f"backend {self.name!r} declares no delta fold")


def score_stream(stream, assignments, chunk_edges: int = 1 << 22,
                 comm_volume: bool = True, weights=None):
    """Score one or more existing assignments against the stream in ONE
    pass: {k: (cut, total, balance, cv)}. ``assignments`` is a dict
    {k: int array[V]}. The native scorer is used when built; this is the
    single host-side scoring implementation shared by partition_multi
    and the CLI's --score-only mode (the reference's standalone
    edge_cut_score() use case)."""
    import numpy as np

    from sheep_tpu.core import native, pure

    use_native = native.available()
    n = stream.num_vertices
    cs = stream.clamp_chunk_edges(chunk_edges)
    cut = {k: 0 for k in assignments}
    total = 0
    cv_parts = {k: [] for k in assignments}
    for chunk in stream.chunks(cs):
        e = np.asarray(chunk, dtype=np.int64).reshape(-1, 2)
        first = True
        for k, a in assignments.items():
            if use_native:
                c, tt = native.score_chunk(e, a, n)
            else:
                c, tt, _, _ = pure.edge_cut_score(e, a, k,
                                                  comm_volume=False)
            cut[k] += int(c)
            if first:
                total += int(tt)
                first = False
            if comm_volume:
                cv_parts[k].append(
                    native.cut_pairs(e, a, n, k) if use_native
                    else pure.cut_pairs(e, a, k))
    out = {}
    for k, a in assignments.items():
        cv = (int(len(np.unique(np.concatenate(cv_parts[k]))))
              if cv_parts[k] else 0) if comm_volume else None
        out[k] = (cut[k], total, pure.part_balance(a, k, weights), cv)
    return out


def register(cls: Type[Partitioner]) -> Type[Partitioner]:
    _REGISTRY[cls.name] = cls
    return cls


def list_backends() -> List[str]:
    return sorted(_REGISTRY)


def get_backend(name: str, **kw) -> Partitioner:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r}; available: {', '.join(list_backends())}"
        ) from None
    return cls(**kw)
