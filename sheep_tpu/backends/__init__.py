from sheep_tpu.backends.base import Partitioner, get_backend, list_backends, register  # noqa: F401

# Import concrete backends for registration side effects. Each import is
# guarded: a backend that cannot initialize in this environment (e.g. the
# native .so not built yet) simply stays unregistered.
from sheep_tpu.backends import pure_backend  # noqa: F401

try:
    from sheep_tpu.backends import cpu_backend  # noqa: F401
except Exception:  # pragma: no cover - native lib absent
    pass

try:
    from sheep_tpu.backends import tpu_backend  # noqa: F401
except Exception:  # pragma: no cover - jax absent/broken
    pass

try:
    from sheep_tpu.backends import tpu_sharded_backend  # noqa: F401
except Exception:  # pragma: no cover - jax absent/broken
    pass

try:
    from sheep_tpu.backends import tpu_bigv_backend  # noqa: F401
except Exception:  # pragma: no cover - jax absent/broken
    pass
