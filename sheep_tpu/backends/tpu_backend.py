"""JAX/TPU backend, registered as ``tpu`` (SURVEY.md §2, north star).

Single-device streaming pipeline (the sharded multi-device path lives in
``sheep_tpu/parallel``):

  pass 1  degrees        scatter-add per chunk           (device)
  sort    elim order     one int64 key sort              (device)
  pass 2  tree build     constraint-rewrite fixpoint     (device, O(V+C) + capped tables)
  split   tree split     two linear passes over O(V)     (host)
  pass 3  scoring        gathered counters               (device)

All chunk steps are jitted with static shapes (last chunk padded with the
sentinel vertex n), so the whole stream reuses one compiled program per
phase — no recompilation across chunks (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from sheep_tpu import obs
from sheep_tpu.analysis import sanitize
from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.io.devicestream import is_device_stream, note_device_chunks
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.ops import score as score_ops
from sheep_tpu.ops import split as split_ops
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range
from sheep_tpu.utils.prefetch import H2DRing, prefetch, prefetch_batched
from sheep_tpu.utils.residency import ResidencyManager


def pad_chunk(chunk: np.ndarray, size: int, n: int) -> np.ndarray:
    """Pad a (c, 2) chunk to (size, 2) int32 with the sentinel vertex n.

    The sentinel is inert in every op: degree slot n is dropped, oriented
    edges (n, n) are inactive, scoring treats n as invalid.
    """
    c = np.asarray(chunk, dtype=np.int64)
    if np.any(c >= np.iinfo(np.int32).max):
        # backstop only: partition() rejects n > MAX_TPU_VERTICES up
        # front, so this fires only for ids beyond a user-supplied
        # (too-small) --num-vertices
        raise ValueError("vertex id >= 2^31 in chunk; ids must fit int32 "
                         "on TPU backends (use --backend cpu)")
    out = np.full((size, 2), n, dtype=np.int32)
    out[: len(c)] = c
    return out


class _ChunkCache:
    """Device-resident cache of padded edge chunks, shared by the three
    streaming passes (degrees / build / score).

    The pipeline reads the same chunks once per pass; without a cache
    every pass re-crosses the host->device link, which on the tunneled
    bench chip runs at ~43 MB/s (tools/out/*/probe_timing.txt) and even
    on a co-located host costs a PCIe crossing per pass. Chunks are kept
    on device while they fit ``budget`` bytes; a graph bigger than the
    budget keeps a cached prefix and streams the rest, so the saving
    degrades gradually. Filling is prefix-ordered and exception-safe:
    chunk i is cached only with chunks [0, i) already cached, so a
    partially-filled cache is always a valid prefix of the stream."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.used = 0
        self.chunks: list = []
        self.complete = False


class _ChunkCacheReader:
    """Read-only view of a :class:`_ChunkCache` held by another job
    (ISSUE 16): serves the filler's cached prefix but never appends.
    A budget of -1 makes :func:`_device_chunks`' grow test false on
    the first chunk, so the prefix-fill invariant keeps exactly one
    writer while any number of interleaved jobs read — the daemon's
    dispatch thread serializes all access, so no further locking is
    needed. A reader that outruns the filler simply streams the rest
    itself (same chunks, no sharing benefit past the prefix)."""

    budget = -1

    def __init__(self, cache: "_ChunkCache"):
        self._cache = cache

    @property
    def chunks(self):
        return self._cache.chunks

    @property
    def used(self):
        return self._cache.used

    @property
    def complete(self):
        return self._cache.complete

    @complete.setter
    def complete(self, value):
        # unreachable via _device_chunks (a reader's grow flag drops
        # on the first chunk); forwarded rather than raising so a
        # future caller setting it stays benign
        self._cache.complete = value


def _upload_chunks(stream, cs: int, n: int, start_chunk: int,
                   ring: int = 1, stats=None):
    """Padded (cs, 2) int32 DEVICE chunks from ``start_chunk`` on.

    Device streams (:mod:`sheep_tpu.io.devicestream` protocol —
    counter-based generators like
    :class:`~sheep_tpu.io.generators.RmatHashStream`) materialize each
    chunk directly in device memory — no host generation, no
    host->device upload, zero host bytes per chunk (measured 92 s of a
    254 s RMAT-22 bench through a degraded tunnel link). File/memory
    streams take the staged path: read + parse + pad of upcoming
    chunks on the prefetch worker, with up to ``ring`` pre-padded
    blocks' device_put transfers issued ahead of the dispatch chain
    (:class:`~sheep_tpu.utils.prefetch.H2DRing`) — the synchronous
    ``jnp.asarray`` this replaces serialized every transfer into the
    dispatch critical path. ``stats`` collects the ingest counters
    (``h2d_staged_ms`` / ``h2d_blocked_ms`` / ``h2d_staged_bytes`` /
    ``device_stream_chunks``)."""
    if is_device_stream(stream):
        for i in range(start_chunk, stream.num_device_chunks(cs)):
            note_device_chunks(stats)
            yield stream.device_chunk(i, cs, n)
        return
    with prefetch(pad_chunk(c, cs, n)
                  for c in stream.chunks(cs, start_chunk=start_chunk)) as pf, \
            H2DRing(pf, depth=max(1, ring), stats=stats) as staged:
        # with-scope = the structural close the resource rule checks:
        # a consumer abandoning this generator closes the ring (its
        # staged HBM drains) and pf deterministically
        for dev in staged:
            yield dev


def _residency_chunks(stream, cs: int, n: int, rm, start_chunk: int,
                      ring: int = 1, stats=None):
    """Serve chunks through the residency manager (ISSUE 20): resident
    ids come straight from HBM; the first miss falls through to the
    stream (the disk tier — every chunk is reconstructible from its
    on-disk bytes), re-uploading and re-offering each chunk for
    residence. The chunk just yielded stays LEASED while it is the
    freshest serve; the lease is dropped just before the NEXT
    admission so the eviction scans that admission may trigger see it
    as reclaimable — dropping the head anchor instead (because the
    tail chunk was pinned) would cost every later pass its prefix
    hits. Correctness never depends on the lease: eviction only drops
    the manager's reference, and a consumer still folding the chunk
    keeps the device buffer alive through its own reference."""
    idx = start_chunk
    leased = None
    try:
        while True:
            ref = rm.get(idx)
            if ref is None:
                break
            rm.lease(idx)
            if leased is not None:
                rm.release(leased)
            leased = idx
            yield ref
            idx += 1
        if not rm.complete:
            for d in _upload_chunks(stream, cs, n, idx, ring, stats):
                if leased is not None:
                    rm.release(leased)
                    leased = None
                rm.admit(idx, d, int(d.size) * 4)
                rm.lease(idx)
                leased = idx
                yield d
                idx += 1
            if start_chunk == 0:
                rm.note_stream_end(idx)
    finally:
        if leased is not None:
            rm.release(leased)


def _device_chunks(stream, cs: int, n: int, cache, start_chunk: int,
                   ring: int = 1, stats=None):
    """Yield padded (cs, 2) int32 chunks as DEVICE arrays, serving and
    filling ``cache`` when iterating from the stream head. ``cache``
    is a legacy prefix :class:`_ChunkCache` (or a reader view), a
    :class:`~sheep_tpu.utils.residency.ResidencyManager` (eviction +
    reload — the out-of-core regime), or None."""
    if isinstance(cache, ResidencyManager):
        yield from _residency_chunks(stream, cs, n, cache, start_chunk,
                                     ring, stats)
        return
    if cache is None or start_chunk != 0:
        yield from _upload_chunks(stream, cs, n, start_chunk, ring, stats)
        return
    yield from cache.chunks
    if cache.complete:
        return
    grow = True
    for d in _upload_chunks(stream, cs, n, len(cache.chunks), ring, stats):
        nb = int(d.size) * 4
        if grow and cache.used + nb <= cache.budget:
            cache.chunks.append(d)
            cache.used += nb
        else:
            grow = False
        yield d
    if grow:
        cache.complete = True


def _device_hbm_bytes(purpose: str = "the chunk cache") -> int:
    """Reported (or generation-inferred) HBM bytes of the default
    device; 0 when nothing trustworthy is known."""
    dev = jax.local_devices()[0]
    try:
        stats = dev.memory_stats() or {}
        hbm = int(stats.get("bytes_limit", 0))
    except Exception:
        hbm = 0
    if hbm <= 0:
        # no reported limit: infer only from a known device generation;
        # an unknown accelerator gets 0 rather than a guessed budget
        # that could OOM it (SHEEP_CACHE_BYTES overrides). Exact kind
        # match first so a future kind merely *containing* one of these
        # substrings (with different HBM) prefers its own entry, and
        # log the inference so an OOM is traceable to it.
        kind = getattr(dev, "device_kind", "").lower()
        known = {"v5 lite": 16, "v5e": 16, "v4": 32, "v5p": 95, "v6": 32}
        g = known.get(kind) or next(
            (g for key, g in known.items() if key in kind), 0)
        hbm = g << 30
        if hbm:
            import sys

            # the override differs by purpose: SHEEP_CACHE_BYTES only
            # budgets the chunk cache; the dispatch batch is overridden
            # by its own knob — advising the wrong one sends an OOMing
            # operator in circles
            override = "SHEEP_CACHE_BYTES" \
                if purpose == "the chunk cache" else "--dispatch-batch N"
            print(f"note: device reports no bytes_limit; inferring "
                  f"{g} GiB HBM from device_kind {kind!r} for {purpose} "
                  f"(override with {override})",
                  file=sys.stderr)
    return hbm


def _chunk_cache_budget(n: int, chunk_edges: int,
                        dispatch_batch: int = 1, inflight: int = 1,
                        donate: bool = False, h2d_ring: int = 0) -> int:
    """Bytes of HBM safely spendable on cached chunks: the device limit
    minus the build phase's modeled peak (including the batched
    dispatch's [N, C] staging blocks) and a safety margin.

    0 (cache disabled) on cpu-jax — there the "device" IS host RAM, so
    caching would duplicate the stream in memory to save a transfer that
    does not exist — and 0 when the accelerator does not report a real
    bytes_limit (no basis for a budget). An explicit SHEEP_CACHE_BYTES
    wins EVERYWHERE, including cpu-jax: the override is how the
    out-of-core residency plane (ISSUE 20) is engaged and exercised —
    its spill/reload/boundary machinery is platform-independent, and
    the exactness contract (tiny budget == unconstrained oracle, bit
    for bit) must be testable without an accelerator."""
    from sheep_tpu.utils.membudget import build_phase_bytes

    env = os.environ.get("SHEEP_CACHE_BYTES")
    if env is not None:
        return max(0, int(env))
    if jax.default_backend() == "cpu":
        return 0
    hbm = _device_hbm_bytes()
    reserve = build_phase_bytes(
        n, chunk_edges, dispatch_batch=dispatch_batch,
        inflight=inflight, donate=donate,
        h2d_ring=h2d_ring)["total_bytes"] + (1 << 30)
    return max(0, int(0.9 * hbm) - reserve)


def resolve_dispatch_batch(dispatch_batch: int, n: int, cs: int,
                           inflight: int = 1,
                           donate: bool = False,
                           h2d_ring: int = 0) -> int:
    """The one auto-sizing rule for ``dispatch_batch`` (shared by the
    single-device and sharded backends): explicit N passes through,
    0 (auto) resolves to per-segment on cpu-jax — host dispatch is
    cheap there and the adaptive driver's compaction/host-tail schedule
    wins — and otherwise to the largest N whose O(N*C) staging fits the
    HBM model (utils/membudget.dispatch_batch_for). ``inflight``,
    ``donate`` and ``h2d_ring`` thread the in-flight pipeline's D-deep
    staging, the donation credit and the staged-ring blocks into that
    model."""
    if dispatch_batch != 0:
        return max(1, int(dispatch_batch))
    if jax.default_backend() == "cpu":
        return 1
    hbm = _device_hbm_bytes(purpose="the dispatch batch")
    if hbm <= 0:
        return 1
    from sheep_tpu.utils.membudget import dispatch_batch_for

    return dispatch_batch_for(int(0.9 * hbm), n, cs, inflight=inflight,
                              donate=donate, h2d_ring=h2d_ring)


def resolve_inflight(inflight: int) -> int:
    """Auto-sizing rule for the dispatch pipeline depth (shared by the
    single-device and sharded backends): explicit D >= 1 passes
    through; 0 (auto) resolves to 2 (double-buffered — one execution
    materializing while the previous one's stats word is pulled) on
    accelerators and 1 (synchronous) on cpu-jax, where "device" work
    shares the host's cores and there is no link RTT to hide."""
    if inflight != 0:
        return max(1, int(inflight))
    return 1 if jax.default_backend() == "cpu" else 2


def resolve_h2d_ring(h2d_ring: int) -> int:
    """Auto-sizing rule for the staged H2D ring depth (shared by the
    tpu driver and the served engine): explicit D >= 1 passes through;
    0 (auto) resolves to 2 on accelerators — the transfer of block i+2
    is in flight while block i folds, so ``h2d_blocked_ms`` collapses
    toward 0 the way ``device_gap_ms`` does at inflight >= 2 — and 1
    on cpu-jax, where device_put is a host-memory copy with no link to
    hide (depth 1 still stages one block ahead, and is bit-identical
    at every depth). Device streams never stage, whatever this says."""
    if h2d_ring != 0:
        return max(1, int(h2d_ring))
    return 1 if jax.default_backend() == "cpu" else 2


def _device_chunk_groups(stream, cs: int, n: int, cache, start_chunk: int,
                         batch: int, ring: int = 1, stats=None):
    """Yield lists of up to ``batch`` padded (cs, 2) int32 DEVICE chunks
    — the staged groups of the batched segment dispatch.

    Host-format streams stage a FULL group of parsed + padded chunks on
    the prefetch worker (:func:`prefetch_batched`) and feed the whole
    group through the staged H2D ring — the transfers for ``ring``
    upcoming groups are in flight while the current enlarged device
    execution runs, so neither the N host reads NOR the N uploads of
    the next batched program sit in the dispatch chain;
    device-synthesizing (:func:`is_device_stream`) and cache-served
    chunks group over the plain per-chunk iterator (no host bytes to
    stage, and the cache's prefix-fill invariant stays in one place)."""
    if batch <= 1:
        for d in _device_chunks(stream, cs, n, cache, start_chunk,
                                ring, stats):
            yield [d]
        return
    if cache is None and not is_device_stream(stream):
        # with-exit is the deterministic worker cancel on abandonment
        # (the in-flight pipeline's discard/backstop paths close this
        # generator mid-stream): drain + join — and drop the ring's
        # staged HBM — instead of waiting for the GC
        with prefetch_batched(
                (pad_chunk(c, cs, n)
                 for c in stream.chunks(cs, start_chunk=start_chunk)),
                batch) as pf, \
                H2DRing(pf, depth=max(1, ring), stats=stats) as staged:
            for dev_group in staged:
                yield list(dev_group)
        return
    group: list = []
    for d in _device_chunks(stream, cs, n, cache, start_chunk,
                            ring, stats):
        group.append(d)
        if len(group) == batch:
            yield group
            group = []
    if group:
        yield group


@register
class TpuBackend(Partitioner):
    name = "tpu"
    supports_checkpoint = True
    supports_multidevice = False  # single-device; see sheep_tpu/parallel
    supports_incremental = True   # partition_update via _fold_delta

    def __init__(self, chunk_edges: int = 1 << 22, lift_levels: int = 0,
                 alpha: float = 1.0, segment_rounds: int = 2,
                 warm_schedule=None, cache_chunks: bool = True,
                 host_tail_threshold: int = -1,
                 carry_tail: Optional[bool] = None,
                 tail_overlap: Optional[bool] = None,
                 stale_reuse: int = 1,
                 dispatch_batch: int = 0,
                 inflight: int = 0,
                 donate_buffers: Optional[bool] = None,
                 h2d_ring: int = 0):
        self.chunk_edges = chunk_edges
        self.lift_levels = lift_levels
        self.alpha = alpha
        # fixpoint rounds per device execution; bounding each call keeps
        # accelerator executions short (long single executions tripped the
        # TPU worker watchdog) while staying bit-identical to monolithic
        self.segment_rounds = segment_rounds
        # one cheap 8-level round before any full-depth round: a
        # full-buffer round costs ~lift_levels x width in gathers, most
        # slots retire early without long jumps, and the dedup/compaction
        # it unlocks shrinks every later round. Measured on the v5e
        # (tools/tune_fixpoint.py, RMAT-20): build 44.9s -> 10.5s
        # together with the C/2 host-tail handoff.
        self.warm_schedule = ((1, 8),) if warm_schedule is None \
            else tuple(warm_schedule)
        self.cache_chunks = cache_chunks
        # -1 = platform default: C/2 on an accelerator (device rounds are
        # expensive relative to the native host pass), auto (C/8, min
        # 2^16) on cpu-jax where the measured sweet spot is later handoff
        self.host_tail_threshold = host_tail_threshold
        # carry the fixpoint tail of intermediate chunks into the next
        # chunk's fold instead of host-finishing each one — saves the
        # per-chunk O(V) table round-trip and the serialized native
        # tail pass; one host tail remains, after the last chunk.
        # Default OFF (None -> False): measured at RMAT-20x16 on
        # cpu-jax, carrying makes the DEVICE grind the displacement
        # cascades the native pass resolves in O(chain) — device rounds
        # 18 -> 30, build 44s -> 178s, identical output (BASELINE.md
        # "carry-over tails"). Kept as an option because the trade
        # reverses only when the per-chunk O(V) round-trip is extremely
        # expensive (tunnel-grade links) — sweep --carry-tail on-chip
        # before ever defaulting it on.
        self.carry_tail = carry_tail
        # overlap each chunk's host tail with the NEXT chunk's device
        # rounds: the tail is resolved by the native pass in a worker
        # thread and re-enters a later fold as O(changed) delta
        # constraints (ops/elim.py host_tail_delta) instead of an O(V)
        # table push — the device never waits for the host. Same unique
        # forest (constraint-multiset argument; pinned by
        # tests/test_tail_overlap.py). Default OFF pending the on-chip
        # sweep; mutually exclusive with carry_tail.
        self.tail_overlap = tail_overlap
        # full segments per lifting-stack rebuild (1 = per-segment
        # hoisting; K > 1 reuses the stack across K segments — see
        # elim.py fold_segment_pos_stale; A/B axis in tune_fixpoint)
        self.stale_reuse = stale_reuse
        # batched segment dispatch (ops/elim.py fold_segments_batch):
        # stage N streamed chunks as one padded [N, C] oriented block
        # and fold them in single bounded device programs — one packed
        # stats sync per execution instead of per segment. 0 = auto:
        # per-segment on cpu-jax (host dispatch is cheap there and the
        # adaptive driver's compaction/host-tail schedule wins), else
        # the largest N whose O(N*C) staging fits the HBM model
        # (utils/membudget.dispatch_batch_for). The forest is
        # bit-identical either way (the fixpoint is unique).
        if dispatch_batch < 0:
            raise ValueError("dispatch_batch must be >= 0 (0 = auto)")
        self.dispatch_batch = dispatch_batch
        # asynchronous dispatch pipeline depth (ops/elim.py
        # fold_segments_pipelined): keep up to D issued batched
        # executions whose stats words are unread futures, converting
        # each to host ints one-behind so the device never waits for a
        # host read/orient/pad and the host never waits for a device
        # program. 0 = auto (2 on accelerators, 1 = synchronous on
        # cpu-jax); any D yields the bit-identical forest (fixpoint
        # uniqueness — tests/test_inflight.py).
        if inflight < 0:
            raise ValueError("inflight must be >= 0 (0 = auto)")
        self.inflight = inflight
        # donate the carried table + staging blocks into each batched
        # execution so XLA reuses their buffers for the outputs instead
        # of double-buffering across executions (None = auto: on
        # whenever the batched/pipelined dispatch runs; results are
        # identical either way — donation is pure buffer aliasing)
        self.donate_buffers = donate_buffers
        # staged H2D ring depth (utils/prefetch.H2DRing): keep up to D
        # pre-padded host blocks' device_put transfers issued ahead of
        # the dispatch chain so the upload of block i+D overlaps the
        # fold of block i. 0 = auto (2 on accelerators, 1 on cpu-jax);
        # bit-identical at every depth (the ring changes WHEN transfers
        # are issued, never what bits arrive). Device streams
        # (io/devicestream.py) skip staging entirely.
        if h2d_ring < 0:
            raise ValueError("h2d_ring must be >= 0 (0 = auto)")
        self.h2d_ring = h2d_ring
        if dispatch_batch > 1 and (carry_tail or tail_overlap):
            raise ValueError("dispatch_batch > 1 folds whole segments on "
                             "device; it excludes the per-chunk tail "
                             "strategies (carry_tail / tail_overlap)")
        if inflight > 1 and (carry_tail or tail_overlap):
            raise ValueError("inflight > 1 pipelines whole batched "
                             "executions; it excludes the per-chunk tail "
                             "strategies (carry_tail / tail_overlap)")
        if carry_tail and tail_overlap:
            raise ValueError("carry_tail and tail_overlap are mutually "
                             "exclusive tail strategies")

    def _resolve_inflight(self) -> int:
        if self.inflight == 0 and (self.carry_tail or self.tail_overlap):
            return 1  # auto defers to an explicit per-chunk tail strategy
        return resolve_inflight(self.inflight)

    def _resolve_dispatch_batch(self, n: int, cs: int,
                                inflight: int = 1,
                                donate: bool = False,
                                h2d_ring: int = 0) -> int:
        if self.dispatch_batch == 0 and (self.carry_tail or
                                         self.tail_overlap):
            return 1  # auto defers to an explicit per-chunk tail strategy
        return resolve_dispatch_batch(self.dispatch_batch, n, cs,
                                      inflight=inflight, donate=donate,
                                      h2d_ring=h2d_ring)

    def _fold_delta(self, state, edges) -> None:
        """Incremental fold (ISSUE 15): stage the delta batch as
        padded [N, C] blocks and fold them into the converged carried
        table with the EXISTING batched dispatch
        (``ops/elim.py fold_segments_batch``) under the state's
        anchored order — one bounded device program per group, the
        same unique fixpoint any dispatch shape lands on. O(Δ) device
        work; the vertex-space minp crosses to/from position space
        only at the batch boundary."""
        n = state.n
        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        if not len(e):
            return
        # power-of-two delta chunk keeps the set of compiled program
        # shapes logarithmic across arbitrary delta sizes
        cs = elim_ops.pow2_at_least(min(len(e), self.chunk_edges),
                                    floor=1 << 10)
        batch_n = self._resolve_dispatch_batch(n, cs)
        pos_sent = np.concatenate([state.pos.astype(np.int32),
                                   np.asarray([n], np.int32)])
        order_sent = np.concatenate([state.order,
                                     np.asarray([n], np.int64)])
        pos_dev = jnp.asarray(pos_sent)
        P = jnp.asarray(state.minp[order_sent])
        stats = state.stats
        chunks = [pad_chunk(e[off: off + cs], cs, n)
                  for off in range(0, len(e), cs)]
        for g0 in range(0, len(chunks), batch_n):
            group = chunks[g0: g0 + batch_n]
            # designed upload window: delta batches are host arrays by
            # definition (they arrived over a wire/log); one staged
            # transfer per bounded group, off the steady-state path
            block = jnp.asarray(  # sheeplint: h2d-ok
                np.stack(group))
            loB, hiB = elim_ops.orient_chunks_batch_pos(block, pos_dev,
                                                        n)
            P, rounds = elim_ops.fold_segments_batch(
                P, loB, hiB, n, segment_rounds=self.segment_rounds,
                stats=stats, donate=False)
            stats["update_rounds"] = \
                stats.get("update_rounds", 0) + int(rounds)
        # designed pull: the converged table is the update's product
        state.minp = np.asarray(P[pos_dev])  # sheeplint: sync-ok

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        from sheep_tpu.utils import checkpoint as ckpt
        from sheep_tpu.utils.fault import maybe_fail

        t = {}
        ckpt_degraded0 = ckpt.degraded_events()
        # right-size the chunk for small graphs so a tiny input doesn't
        # pad out to the full default chunk shape
        cs = stream.clamp_chunk_edges(self.chunk_edges)
        t0 = time.perf_counter()
        n = stream.num_vertices
        check_tpu_vertex_range(n, self.name)
        root_sp = obs.begin("partition", backend=self.name, k=int(k),
                            n=int(n), chunk_edges=int(cs))
        stats_acc = obs.stats_accumulator()
        m_cheap = stream.num_edges_cheap
        obs.progress(backend=self.name, k=int(k), edges_total=m_cheap,
                     chunks_total=-(-m_cheap // cs) if m_cheap else None)
        carry_mode = bool(self.carry_tail)
        meta = ckpt.stream_meta(stream, k, cs, weights=weights,
                                alpha=self.alpha, comm_volume=comm_volume,
                                state_format="minp_carry" if carry_mode
                                else "minp")
        state = ckpt.resume_state(checkpointer, meta, resume)
        from_phase = ckpt.phase_index(state.phase) if state else 0

        # Device accumulation is int32; flush to a host int64 accumulator
        # before a vertex could possibly see 2^31 endpoints, so trillion-edge
        # streams cannot overflow (cross-chunk totals live host-side).
        flush_every = degrees_ops.flush_every_for(cs)
        if state:
            deg_host = state.arrays["deg"].copy()
        else:
            deg_host = np.zeros(n, dtype=np.int64)
        inflight_n = self._resolve_inflight()
        ring_n = resolve_h2d_ring(self.h2d_ring)
        # the membudget model counts ring staging only for streams that
        # actually stage — a device stream synthesizes in place and
        # holds no pre-transferred blocks
        ring_model = 0 if is_device_stream(stream) else ring_n
        donate = True if self.donate_buffers is None else self.donate_buffers
        batch_n = self._resolve_dispatch_batch(n, cs, inflight=inflight_n,
                                               donate=donate,
                                               h2d_ring=ring_model)
        # the donating fold only runs on the pipelined/batched branch
        # (batch_n == 1 == inflight_n selects the adaptive per-segment
        # driver below); crediting donation to the HBM model on a path
        # that never donates would under-reserve by a full minp table
        if batch_n == 1 and inflight_n == 1:
            donate = False
        cache_budget = _chunk_cache_budget(n, cs, dispatch_batch=batch_n,
                                           inflight=inflight_n,
                                           donate=donate,
                                           h2d_ring=ring_model) \
            if self.cache_chunks else 0
        # ONE stats dict across all three streaming passes: the ingest
        # counters (h2d_* / device_stream_chunks) accumulate wherever
        # chunks cross (or don't cross) the link, and the build phase
        # adds the dispatch counters to the same record
        build_stats: dict = {}
        # residency-managed chunk tier (ISSUE 20): same prefix-cache
        # fast path when the stream fits the budget, spill/reload with
        # checkpoint-boundary eviction when it does not — device memory
        # is a cache over the on-disk stream, not a ceiling. The spill
        # counters land in build_stats -> diagnostics -> bench record.
        cache = ResidencyManager(cache_budget, stats=build_stats) \
            if cache_budget > 0 else None

        def _ckpt_boundary(confirmed_idx: int) -> None:
            # checkpoint boundaries are the residency eviction points:
            # chunks behind the confirmed index can no longer be
            # re-read by any retry (resume starts at confirmed_idx)
            if isinstance(cache, ResidencyManager):
                cache.boundary(confirmed_idx)
        sp = obs.begin("degrees")
        obs.progress(phase="degrees", chunks_done=0, edges_done=0)
        # anchored-order streams (delta: inputs, io/deltalog.py): the
        # elimination order derives from the BASE segment's degrees
        # only — the contract that makes the incremental path
        # bit-identical to this one-shot build. The anchor pass never
        # touches the chunk cache (its chunks are a different stream
        # than the build/score passes'); build fills the cache with
        # the full surviving multiset as usual.
        anchored = bool(getattr(stream, "order_anchor", False))
        if from_phase == 0:
            start = state.chunk_idx if state else 0
            deg = degrees_ops.init_degrees(n)
            since_flush = 0
            idx = start
            # read+parse+pad of chunk i+1 overlaps the device fold of i;
            # the staged ring keeps its H2D transfer off the chain too
            for padded in _device_chunks(
                    stream.anchor_stream() if anchored else stream,
                    cs, n, None if anchored else cache, start,
                    ring_n, build_stats):
                deg = degrees_ops.degree_chunk(deg, padded, n)
                since_flush += 1
                idx += 1
                maybe_fail("degrees", idx - start)
                obs.chunk_progress(idx, cs, m_cheap)
                at_ckpt = checkpointer is not None and checkpointer.due(idx - start)
                if since_flush >= flush_every or at_ckpt:
                    # designed flush sync: int32 device accumulator ->
                    # int64 host totals
                    deg_host += np.asarray(deg[:n],  # sheeplint: sync-ok
                                           dtype=np.int64)
                    deg = degrees_ops.init_degrees(n)
                    since_flush = 0
                if at_ckpt:
                    checkpointer.save("degrees", idx, {"deg": deg_host}, meta)
                    _ckpt_boundary(idx)
            deg_host += np.asarray(deg[:n],  # sheeplint: sync-ok
                                   dtype=np.int64)
        t["degrees"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        with obs.span("sort"):
            # positions are int32 ranks; degree values only matter
            # ordinally, so clip the int64 totals into int32 for the
            # device sort via rankdata
            deg_rank = degrees_ops.rank_clip_i32(deg_host)
            deg_dev = jnp.asarray(deg_rank, dtype=jnp.int32)
            pos, order = order_ops.elimination_order(deg_dev, n)
            # tiny host pull as the completion barrier: block_until_ready
            # is not a real barrier on a tunneled device (BASELINE.md
            # fact 3)
            np.asarray(pos[:1])  # sheeplint: sync-ok
            t["sort"] = time.perf_counter() - t0
        pos_host_cache = None

        t0 = time.perf_counter()
        sp = obs.begin("build")
        obs.progress(phase="build", chunks_done=0, edges_done=0)
        total_rounds = 0
        if state and from_phase >= 2:
            minp = jnp.asarray(state.arrays["minp"])
        else:
            pos_host_cache = np.asarray(pos[:n])  # sheeplint: sync-ok
            tail_at = self.host_tail_threshold
            if tail_at < 0:
                tail_at = cs // 2 if jax.default_backend() != "cpu" else 0
            from contextlib import nullcontext

            from sheep_tpu.core import native as native_mod

            # ---- fault-tolerant build (ISSUE 9 tentpole) --------------
            # The whole streaming build runs as one retryable ATTEMPT
            # against ``snap``, an in-memory snapshot of the last
            # confirmed state (vertex-space minp + next chunk index —
            # exactly a checkpoint's payload, banked whenever one is
            # saved). A RESOURCE_EXHAUSTED-class fault degrades the
            # dispatch footprint (membudget.degraded_dispatch halves
            # dispatch_batch/inflight, the chunk cache is dropped) and
            # re-folds from the snapshot; a device-loss-class fault
            # persists the snapshot through the Checkpointer, best-effort
            # reinitializes the device in-process, and re-folds the same
            # way. Bit-identical either way: restart-from-snapshot is the
            # PR-8 resume semantics, and the fixpoint is unique in the
            # constraint multiset regardless of batch/inflight shape.
            # The carried forest lives in POSITION space on device (P);
            # snapshots/checkpoints keep the stable vertex-space minp
            # encoding, so conversions happen only at those boundaries.
            # In carry mode the in-flight actives are part of the state
            # and snapshot alongside (position space — pos is a pure
            # function of the fingerprinted stream, stable across
            # resume).
            snap = {"idx": 0, "minp": None, "carry": None}
            if state and state.phase == "build":
                snap["idx"] = state.chunk_idx
                snap["minp"] = state.arrays["minp"]
                if carry_mode and "carry_lo" in state.arrays:
                    snap["carry"] = (state.arrays["carry_lo"],
                                     state.arrays["carry_hi"])
            cfg = {"batch": batch_n, "inflight": inflight_n,
                   "donate": donate, "ring": ring_n}

            def _build_attempt():
                nonlocal total_rounds
                start = snap["idx"]
                idx = start
                if snap["minp"] is not None:
                    P = jnp.asarray(snap["minp"])[order]
                else:
                    P = jnp.full(n + 1, n, dtype=jnp.int32)
                carry = None
                if carry_mode and snap["carry"] is not None:
                    carry = (jnp.asarray(snap["carry"][0]),
                             jnp.asarray(snap["carry"][1]))
                batch_n = cfg["batch"]
                inflight_n = cfg["inflight"]
                ring_n = cfg["ring"]
                donate = cfg["donate"] and (batch_n > 1 or inflight_n > 1)
                overlap = (bool(self.tail_overlap) and not carry_mode
                           and native_mod.available())
                ov_ctx = elim_ops.TailOverlap(n, pos_host_cache) \
                    if overlap else nullcontext()

                with ov_ctx as ov:

                    def _flush_deltas() -> None:
                        # resolve everything still in flight into P,
                        # synchronously (checkpoint boundaries and the
                        # end of the stream: saved state must be the
                        # complete constraint multiset)
                        nonlocal P, total_rounds
                        ov.drain(True)
                        inj = ov.take_inject()
                        if inj is not None:
                            P, r = elim_ops.fold_edges_adaptive_pos(
                                P, inj[0], inj[1], n,
                                lift_levels=self.lift_levels,
                                segment_rounds=self.segment_rounds,
                                host_tail_threshold=tail_at,
                                stale_reuse=self.stale_reuse,
                                pos_host=pos_host_cache,
                                stats=build_stats)
                            total_rounds += int(r)

                    if (batch_n > 1 or inflight_n > 1) and not carry_mode \
                            and not overlap:
                        # batched segment dispatch, pipelined (ops/
                        # elim.py fold_segments_pipelined): stage
                        # batch_n chunks as one oriented [N, C] block,
                        # fold groups in bounded multi-segment device
                        # programs with up to inflight_n executions in
                        # flight, and pull one packed stats word per
                        # execution ONE-BEHIND — the host's read/orient/
                        # pad overlaps the device fixpoint instead of
                        # alternating with it, and donation reuses the
                        # table/staging buffers across the chain. Warm
                        # schedule / compaction / host tail are
                        # per-segment host decisions and do not apply
                        # here; the forest is the same unique fixpoint
                        # either way.
                        build_stats["dispatch_batch"] = batch_n
                        build_stats["inflight_depth"] = inflight_n
                        groups = _device_chunk_groups(stream, cs, n,
                                                      cache, start,
                                                      batch_n, ring_n,
                                                      build_stats)

                        def staged_groups():
                            sentinel_chunk = None
                            for group in groups:
                                gl = len(group)
                                if gl < batch_n:
                                    if sentinel_chunk is None:
                                        sentinel_chunk = jnp.full(
                                            (cs, 2), n, jnp.int32)
                                    group = group + [sentinel_chunk] * \
                                        (batch_n - gl)
                                loB, hiB = \
                                    elim_ops.orient_chunks_batch_pos(
                                        jnp.stack(group), pos, n)
                                yield loB, hiB, gl

                        # rolling dispatch spans tile the pipelined
                        # build: each one covers confirm-to-confirm (the
                        # counter deltas carry the overlap story —
                        # host_blocked_ms / device_gap_ms); issue/
                        # confirm interleave across groups, so per-group
                        # spans would no longer nest
                        dsp = obs.begin("dispatch", i=idx)

                        def confirmed(gl, rounds, tipP):
                            # returns True to request a flush barrier
                            # when a checkpoint is due: mid-pipeline the
                            # tip table can UNDER-represent a confirmed
                            # group whose budget-exhausted leftovers are
                            # still queued, so the save itself happens
                            # in flushed(), after the driver drains
                            # everything issued
                            nonlocal idx, dsp
                            stats_acc.absorb(build_stats)
                            dsp.end(rounds=int(rounds))
                            due = False
                            if gl is not None:
                                prev = idx
                                idx += gl
                                obs.chunk_progress(idx, cs, m_cheap)
                                for i in range(prev + 1, idx + 1):
                                    maybe_fail("build", i - start,
                                               kinds=("kill", "oom",
                                                      "device"))
                                due = checkpointer is not None and \
                                    checkpointer.due_span(prev - start,
                                                          idx - start)
                            dsp = obs.begin("dispatch", i=idx)
                            return due

                        def flushed(tipP):
                            # pipeline fully drained: idx (advanced
                            # through every group confirmed during the
                            # drain) and the table now agree exactly —
                            # the sound cut for both the durable
                            # checkpoint and the in-memory retry
                            # snapshot
                            with sanitize.sync_ok("flush-checkpoint"):
                                arrays = {
                                    "deg": deg_host,
                                    "minp": np.asarray(tipP[pos])}  # sheeplint: sync-ok
                            snap["idx"] = idx
                            snap["minp"] = arrays["minp"]
                            if checkpointer is not None:
                                checkpointer.save("build", idx, arrays,
                                                  meta)
                            # the flushed table IS the confirmed state
                            # (durable or in-memory snapshot): chunks
                            # behind it are eviction-safe either way
                            _ckpt_boundary(idx)

                        staged = staged_groups()
                        try:
                            P, rounds = elim_ops.fold_segments_pipelined(
                                P, staged, n,
                                inflight=inflight_n,
                                lift_levels=self.lift_levels,
                                segment_rounds=self.segment_rounds,
                                donate=donate,
                                stats=build_stats,
                                on_confirm=confirmed,
                                on_flush=flushed)
                            total_rounds += int(rounds)
                        finally:
                            # the discard/backstop/fault paths abandon
                            # the staged stream mid-iteration: close
                            # BOTH generators — a for-loop does not
                            # close the iterator it consumes, so
                            # staged.close() alone would leave
                            # _device_chunk_groups (and the prefetch
                            # worker its finally cancels) open until GC
                            staged.close()
                            groups.close()
                            dsp.end()
                        stats_acc.absorb(build_stats)
                    else:
                        for padded in _device_chunks(stream, cs, n,
                                                     cache, start,
                                                     ring_n, build_stats):
                            seg_sp = obs.begin("segment", i=idx)
                            try:
                                if overlap:
                                    # pick up any host-resolved tails
                                    # without waiting; they enter this
                                    # fold as ordinary actives
                                    ov.drain(False)
                                    carry = ov.take_inject()
                                step = \
                                    elim_ops.build_chunk_step_adaptive_pos(
                                        P, padded, pos, pos_host_cache,
                                        n,
                                        lift_levels=self.lift_levels,
                                        segment_rounds=self
                                        .segment_rounds,
                                        warm_schedule=self.warm_schedule,
                                        stats=build_stats,
                                        host_tail_threshold=tail_at,
                                        stale_reuse=self.stale_reuse,
                                        carry=carry,
                                        carry_out=carry_mode or overlap)
                                if carry_mode:
                                    P, rounds, carry = step
                                elif overlap:
                                    P, rounds, tail = step
                                    carry = None
                                    if int(tail[0].shape[0]):
                                        build_stats["overlap_tails"] = \
                                            build_stats.get(
                                                "overlap_tails", 0) + 1
                                        ov.submit(P, tail[0], tail[1])
                                else:
                                    P, rounds = step
                                total_rounds += int(rounds)
                                stats_acc.absorb(build_stats)
                                seg_sp.end(rounds=int(rounds))
                            finally:
                                # idempotent: balances the span when a
                                # fault unwinds mid-chunk so a RECOVERED
                                # run still renders a complete tree
                                seg_sp.end()
                            idx += 1
                            obs.chunk_progress(idx, cs, m_cheap)
                            maybe_fail("build", idx - start,
                                       kinds=("kill", "oom", "device"))
                            if checkpointer is not None and \
                                    checkpointer.due(idx - start):
                                if overlap:
                                    _flush_deltas()
                                arrays = {"deg": deg_host,
                                          "minp": np.asarray(P[pos])}
                                if carry_mode:
                                    arrays["carry_lo"] = \
                                        np.asarray(carry[0])
                                    arrays["carry_hi"] = \
                                        np.asarray(carry[1])
                                snap["idx"] = idx
                                snap["minp"] = arrays["minp"]
                                if carry_mode:
                                    snap["carry"] = (arrays["carry_lo"],
                                                     arrays["carry_hi"])
                                checkpointer.save("build", idx, arrays,
                                                  meta)
                                _ckpt_boundary(idx)
                    if overlap:
                        _flush_deltas()
                if carry_mode and carry is not None \
                        and int(carry[0].shape[0]):
                    # resolve the final carried tail (the stream's ONE
                    # host tail); plain entry point = host-finish
                    # semantics
                    P, rounds = elim_ops.fold_edges_adaptive_pos(
                        P, carry[0], carry[1], n,
                        lift_levels=self.lift_levels,
                        segment_rounds=self.segment_rounds,
                        host_tail_threshold=tail_at,
                        stale_reuse=self.stale_reuse,
                        pos_host=pos_host_cache, stats=build_stats)
                    total_rounds += int(rounds)
                return P

            from sheep_tpu.utils import retry as retry_mod

            def _on_resource():
                # spill before shrink (ISSUE 20): the resident chunks
                # are reclaimable HBM — with spillable bytes the
                # degrade ladder's first rung drops them (and halves
                # the residency budget) with the dispatch knobs
                # UNCHANGED; only a fault with nothing left to spill
                # halves whichever knob the membudget model indicts
                nonlocal cache
                rm = cache if isinstance(cache, ResidencyManager) \
                    else None
                if cache is not None and rm is None:
                    cache.chunks.clear()
                    cache.used = 0
                    cache.complete = False
                    cache.budget = 0
                    cache = None
                nxt = retry_mod.degrade_dispatch(
                    n, cs, cfg["batch"], cfg["inflight"], cfg["donate"],
                    build_stats, snap["idx"],
                    h2d_ring=None if ring_model == 0 else cfg["ring"],
                    residency=rm)
                if rm is not None and rm.budget <= 0:
                    cache = None  # walked to zero: stop probing it
                if nxt is not None:
                    cfg["batch"], cfg["inflight"] = nxt[0], nxt[1]
                    if len(nxt) > 2:
                        cfg["ring"] = nxt[2]

            def _save_snapshot():
                if checkpointer is not None and snap["minp"] is not None:
                    arrays = {"deg": deg_host, "minp": snap["minp"]}
                    if carry_mode and snap["carry"] is not None:
                        arrays["carry_lo"] = snap["carry"][0]
                        arrays["carry_hi"] = snap["carry"][1]
                    checkpointer.save("build", snap["idx"], arrays, meta)

            def _on_device_loss():
                retry_mod.recover_device_loss(build_stats, snap["idx"],
                                              _save_snapshot)

            policy = retry_mod.RetryPolicy()
            while True:
                try:
                    P = _build_attempt()
                    break
                except Exception as exc:
                    # shared classify/budget/count/backoff protocol
                    # (retry.handle_build_fault — the dispatch_retries
                    # trail is gated higher-is-worse by bench_regress);
                    # FATAL and exhausted budgets re-raise inside
                    retry_mod.handle_build_fault(
                        policy, exc, "tpu.build", build_stats,
                        on_resource=_on_resource,
                        on_device_loss=_on_device_loss)
                    stats_acc.absorb(build_stats)
            # an OOM-degraded ring depth carries forward to the score
            # pass: it runs outside the retry harness, so re-staging at
            # the pre-degrade depth on a device that just proved too
            # small would re-OOM unrecovered
            ring_n = cfg["ring"]
            minp = P[pos]
            # real completion barrier (see above)
            np.asarray(minp[:1])  # sheeplint: sync-ok
        t["build"] = time.perf_counter() - t0
        stats_acc.absorb(build_stats)
        sp.end(fixpoint_rounds=int(total_rounds))

        t0 = time.perf_counter()
        with obs.span("split"):
            parent = elim_ops.minp_to_parent(minp, order, n)
            pos_host = pos_host_cache if pos_host_cache is not None \
                else np.asarray(pos[:n])  # sheeplint: sync-ok
            w = deg_host.astype(np.float64) if weights == "degree" else None
            assign_host = split_ops.tree_split_host(parent, pos_host, k,
                                                    weights=w,
                                                    alpha=self.alpha)
            assign = jnp.concatenate(
                [jnp.asarray(assign_host, dtype=jnp.int32),
                 jnp.zeros(1, dtype=jnp.int32)])
            t["split"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        sp = obs.begin("score")
        obs.progress(phase="score", chunks_done=0, edges_done=0)
        cut = total = 0
        cv_chunks = []
        start = 0
        if state and state.phase == "score":
            start = state.chunk_idx
            cut = int(state.arrays["cut"])
            total = int(state.arrays["total"])
            if comm_volume:
                cv_chunks.append(state.arrays["cv_keys"])
        idx = start
        for padded in _device_chunks(stream, cs, n, cache, start,
                                     ring_n, build_stats):
            c, tt = score_ops.score_chunk(padded, assign, n)
            # designed per-chunk score pull (two scalars, one chunk)
            cut += int(c)  # sheeplint: sync-ok
            total += int(tt)  # sheeplint: sync-ok
            if comm_volume:
                score_ops.accumulate_cv_keys(
                    cv_chunks,
                    score_ops.cut_pair_keys_host(padded, assign, n, k))
            idx += 1
            maybe_fail("score", idx - start)
            obs.chunk_progress(idx, cs, m_cheap)
            if checkpointer is not None and checkpointer.due(idx - start):
                cv_chunks = ckpt.save_score_state(
                    checkpointer, idx, cut, total, cv_chunks,
                    {"deg": deg_host, "minp": np.asarray(minp)}, meta,
                    comm_volume)
                _ckpt_boundary(idx)
        cv = int(len(ckpt.compact_cv_keys(cv_chunks))) if comm_volume else None
        # the score pass re-streams (and under a residency budget,
        # re-spills) — absorb its counters so the trace's final totals
        # match the diagnostics instead of stopping at the build phase
        stats_acc.absorb(build_stats)
        from sheep_tpu.core import pure

        balance = pure.part_balance(assign_host, k,
                                    deg_host if weights == "degree" else None)
        t["score"] = time.perf_counter() - t0
        sp.end()
        root_sp.end()
        if checkpointer is not None:
            checkpointer.clear()
        if ckpt.degraded_events() > ckpt_degraded0:
            # lossy recovery happened during THIS run: surface it in
            # the diagnostics so the bench contract / regression gate
            # see the degradation instead of a silently-clean number
            build_stats["checkpoint_degraded"] = \
                ckpt.degraded_events() - ckpt_degraded0

        return PartitionResult(
            assignment=assign_host, k=k, edge_cut=cut, total_edges=total,
            cut_ratio=cut / max(total, 1), balance=balance, comm_volume=cv,
            phase_times=t, backend=self.name,
            # t_* walls and *_ms counters accumulate unrounded (elim.py
            # t_add/_t_ms) and are rounded HERE, at read time, so their
            # sums never drift past the measured wall by per-add
            # rounding quanta
            diagnostics={"fixpoint_rounds": float(total_rounds),
                         **{k: (round(float(v), 3)
                                if k.startswith("t_") or k.endswith("_ms")
                                else float(v))
                            for k, v in build_stats.items()}},
            tree={"parent": np.asarray(parent), "pos": pos_host,
                  "deg": deg_host} if opts.get("keep_tree") else None,
        )
