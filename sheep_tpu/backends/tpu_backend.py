"""JAX/TPU backend, registered as ``tpu`` (SURVEY.md §2, north star).

Single-device streaming pipeline (the sharded multi-device path lives in
``sheep_tpu/parallel``):

  pass 1  degrees        scatter-add per chunk           (device)
  sort    elim order     one int64 key sort              (device)
  pass 2  tree build     constraint-rewrite fixpoint     (device, O(V+C) + capped tables)
  split   tree split     two linear passes over O(V)     (host)
  pass 3  scoring        gathered counters               (device)

All chunk steps are jitted with static shapes (last chunk padded with the
sentinel vertex n), so the whole stream reuses one compiled program per
phase — no recompilation across chunks (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.ops import score as score_ops
from sheep_tpu.ops import split as split_ops
from sheep_tpu.types import PartitionResult
from sheep_tpu.utils.prefetch import prefetch


def pad_chunk(chunk: np.ndarray, size: int, n: int) -> np.ndarray:
    """Pad a (c, 2) chunk to (size, 2) int32 with the sentinel vertex n.

    The sentinel is inert in every op: degree slot n is dropped, oriented
    edges (n, n) are inactive, scoring treats n as invalid.
    """
    c = np.asarray(chunk, dtype=np.int64)
    if np.any(c >= np.iinfo(np.int32).max):
        raise NotImplementedError("vertex ids >= 2^31 not supported yet")
    out = np.full((size, 2), n, dtype=np.int32)
    out[: len(c)] = c
    return out


@register
class TpuBackend(Partitioner):
    name = "tpu"
    supports_multidevice = False  # single-device; see sheep_tpu/parallel

    def __init__(self, chunk_edges: int = 1 << 22, lift_levels: int = 0,
                 alpha: float = 1.0, segment_rounds: int = 2):
        self.chunk_edges = chunk_edges
        self.lift_levels = lift_levels
        self.alpha = alpha
        # fixpoint rounds per device execution; bounding each call keeps
        # accelerator executions short (long single executions tripped the
        # TPU worker watchdog) while staying bit-identical to monolithic
        self.segment_rounds = segment_rounds

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        from sheep_tpu.utils import checkpoint as ckpt
        from sheep_tpu.utils.fault import maybe_fail

        t = {}
        cs = self.chunk_edges
        t0 = time.perf_counter()
        n = stream.num_vertices
        meta = ckpt.stream_meta(stream, k, cs, weights=weights,
                                alpha=self.alpha, comm_volume=comm_volume,
                                state_format="minp")
        state = ckpt.resume_state(checkpointer, meta, resume)
        from_phase = ckpt.phase_index(state.phase) if state else 0

        # Device accumulation is int32; flush to a host int64 accumulator
        # before a vertex could possibly see 2^31 endpoints, so trillion-edge
        # streams cannot overflow (cross-chunk totals live host-side).
        flush_every = max(1, (2**31 - 1) // max(2 * cs, 1))
        if state:
            deg_host = state.arrays["deg"].copy()
        else:
            deg_host = np.zeros(n, dtype=np.int64)
        if from_phase == 0:
            start = state.chunk_idx if state else 0
            deg = degrees_ops.init_degrees(n)
            since_flush = 0
            idx = start
            # read+parse+pad of chunk i+1 overlaps the device fold of i
            for padded in prefetch(pad_chunk(c, cs, n)
                                   for c in stream.chunks(cs, start_chunk=start)):
                deg = degrees_ops.degree_chunk(deg, padded, n)
                since_flush += 1
                idx += 1
                maybe_fail("degrees", idx - start)
                at_ckpt = checkpointer is not None and checkpointer.due(idx - start)
                if since_flush >= flush_every or at_ckpt:
                    deg_host += np.asarray(deg[:n], dtype=np.int64)
                    deg = degrees_ops.init_degrees(n)
                    since_flush = 0
                if at_ckpt:
                    checkpointer.save("degrees", idx, {"deg": deg_host}, meta)
            deg_host += np.asarray(deg[:n], dtype=np.int64)
        t["degrees"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        # positions are int32 ranks; degree values only matter ordinally, so
        # clip the int64 totals into int32 for the device sort via rankdata
        deg_rank = deg_host if deg_host.size == 0 or deg_host.max() < 2**31 \
            else np.argsort(np.argsort(deg_host, kind="stable"), kind="stable")
        deg_dev = jnp.asarray(deg_rank, dtype=jnp.int32)
        pos, order = order_ops.elimination_order(deg_dev, n)
        pos.block_until_ready()
        t["sort"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        build_stats: dict = {}
        if state and from_phase >= 2:
            minp = jnp.asarray(state.arrays["minp"])
            total_rounds = 0
        else:
            if state and state.phase == "build":
                minp = jnp.asarray(state.arrays["minp"])
                start = state.chunk_idx
            else:
                minp = jnp.full(n + 1, n, dtype=jnp.int32)
                start = 0
            total_rounds = 0
            idx = start
            pos_host_cache = np.asarray(pos[:n])  # host tail reuses it
            for padded in prefetch(pad_chunk(c, cs, n)
                                   for c in stream.chunks(cs, start_chunk=start)):
                minp, rounds = elim_ops.build_chunk_step_adaptive(
                    minp, padded, pos, order, n,
                    lift_levels=self.lift_levels,
                    segment_rounds=self.segment_rounds,
                    pos_host=pos_host_cache, stats=build_stats)
                total_rounds += int(rounds)
                idx += 1
                maybe_fail("build", idx - start)
                if checkpointer is not None and checkpointer.due(idx - start):
                    checkpointer.save(
                        "build", idx,
                        {"deg": deg_host, "minp": np.asarray(minp)}, meta)
            minp.block_until_ready()
        t["build"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        parent = elim_ops.minp_to_parent(minp, order, n)
        pos_host = np.asarray(pos[:n])
        w = deg_host.astype(np.float64) if weights == "degree" else None
        assign_host = split_ops.tree_split_host(parent, pos_host, k, weights=w,
                                                alpha=self.alpha)
        assign = jnp.concatenate(
            [jnp.asarray(assign_host, dtype=jnp.int32),
             jnp.zeros(1, dtype=jnp.int32)])
        t["split"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        cut = total = 0
        cv_chunks = []
        start = 0
        if state and state.phase == "score":
            start = state.chunk_idx
            cut = int(state.arrays["cut"])
            total = int(state.arrays["total"])
            if comm_volume:
                cv_chunks.append(state.arrays["cv_keys"])
        idx = start
        for padded in prefetch(pad_chunk(c, cs, n)
                               for c in stream.chunks(cs, start_chunk=start)):
            c, tt = score_ops.score_chunk(padded, assign, n)
            cut += int(c)
            total += int(tt)
            if comm_volume:
                score_ops.accumulate_cv_keys(
                    cv_chunks,
                    score_ops.cut_pair_keys_host(padded, assign, n, k))
            idx += 1
            maybe_fail("score", idx - start)
            if checkpointer is not None and checkpointer.due(idx - start):
                cv_chunks = ckpt.save_score_state(
                    checkpointer, idx, cut, total, cv_chunks,
                    {"deg": deg_host, "minp": np.asarray(minp)}, meta,
                    comm_volume)
        cv = int(len(ckpt.compact_cv_keys(cv_chunks))) if comm_volume else None
        from sheep_tpu.core import pure

        balance = pure.part_balance(assign_host, k,
                                    deg_host if weights == "degree" else None)
        t["score"] = time.perf_counter() - t0
        if checkpointer is not None:
            checkpointer.clear()

        return PartitionResult(
            assignment=assign_host, k=k, edge_cut=cut, total_edges=total,
            cut_ratio=cut / max(total, 1), balance=balance, comm_volume=cv,
            phase_times=t, backend=self.name,
            diagnostics={"fixpoint_rounds": float(total_rounds),
                         **{k: float(v) for k, v in build_stats.items()}},
        )
