"""Multi-device sharded TPU backend, registered as ``tpu-sharded``.

The v5e-8 / multi-host execution strategy (SURVEY.md §2 #9, §7 step 5):
edge chunks round-robin across the ``shards`` mesh axis, per-device partial
forests, butterfly merge over ICI, psum scoring. Thin wrapper around
``ShardedPipeline.run`` (the single implementation of the streaming
loops); falls back gracefully to a 1-device mesh with results identical to
the ``tpu`` backend.
"""

from __future__ import annotations

import numpy as np

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.parallel.pipeline import ShardedPipeline, cached_pipeline
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range


@register
class TpuShardedBackend(Partitioner):
    name = "tpu-sharded"
    supports_checkpoint = True
    supports_multidevice = True
    # incremental repartitioning (ISSUE 19): delta epochs fold through
    # the lockstep batch machinery (_fold_delta below), scored refreshes
    # rescore device-side with one all-reduce (_move_rescore)
    supports_incremental = True

    def __init__(self, chunk_edges: int = 1 << 22, lift_levels: int = 0,
                 alpha: float = 1.0, n_devices: int | None = None,
                 segment_rounds: int = 32, warm_schedule=((1, 8),),
                 dispatch_batch: int = 0, inflight: int = 0,
                 donate_buffers: bool | None = None):
        self.chunk_edges = chunk_edges
        self.lift_levels = lift_levels
        self.alpha = alpha
        self.n_devices = n_devices
        self.segment_rounds = segment_rounds
        self.warm_schedule = tuple(warm_schedule)
        # batched segment dispatch (see ShardedPipeline): 0 = auto
        # (per-segment on cpu-jax; HBM-model-sized N on accelerators),
        # 1 = per-segment, N > 1 = stage N sharded batches per program
        if dispatch_batch < 0:
            raise ValueError("dispatch_batch must be >= 0 (0 = auto)")
        self.dispatch_batch = dispatch_batch
        # asynchronous dispatch pipeline depth for the batched path
        # (see ShardedPipeline.build_step_batch): 0 = auto (2 on
        # accelerators, 1 = synchronous on cpu-jax)
        if inflight < 0:
            raise ValueError("inflight must be >= 0 (0 = auto)")
        self.inflight = inflight
        # donate per-device tables + staging blocks into the batched
        # executions (None = auto: on for the batched/pipelined path)
        self.donate_buffers = donate_buffers

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        # comm_volume defaults True like every other backend (VERDICT r1
        # weak #5 asked for consistency); pass False to skip the host-side
        # O(cut pairs) accumulator on huge runs
        if getattr(stream, "order_anchor", False):
            import jax

            if jax.process_count() > 1:
                from sheep_tpu.types import UnsupportedGraphError

                raise UnsupportedGraphError(
                    "delta: inputs stream single-shard; a multi-host "
                    "mesh cannot byte-range an anchored log — run the "
                    "delta build on a single-host mesh or --backend "
                    "tpu/cpu")
        n = stream.num_vertices
        check_tpu_vertex_range(n, self.name)
        mesh = shards_mesh(self.n_devices)
        # shrink the chunk so small graphs don't pad (and compile) up to
        # the full default chunk shape; shared helper so the backends'
        # chunk sizing (and checkpoint fingerprints) cannot diverge
        cs = stream.clamp_chunk_edges(self.chunk_edges,
                                      parts=mesh.devices.size)
        from sheep_tpu.backends.tpu_backend import resolve_dispatch_batch, \
            resolve_inflight

        inflight = resolve_inflight(self.inflight)
        donate = True if self.donate_buffers is None else self.donate_buffers
        nb = resolve_dispatch_batch(self.dispatch_batch, n, cs,
                                    inflight=inflight, donate=donate)
        pipe = cached_pipeline(n, cs, mesh, lift_levels=self.lift_levels,
                               segment_rounds=self.segment_rounds,
                               warm_schedule=self.warm_schedule,
                               dispatch_batch=nb, inflight=inflight,
                               donate=donate)

        timings: dict = {}
        out = pipe.run(stream, k, alpha=self.alpha, weights=weights,
                       comm_volume=comm_volume, timings=timings,
                       checkpointer=checkpointer, resume=resume)
        return PartitionResult(
            assignment=out["assignment"], k=k, edge_cut=out["edge_cut"],
            total_edges=out["total_edges"],
            cut_ratio=out["edge_cut"] / max(out["total_edges"], 1),
            balance=out["balance"], comm_volume=out["comm_volume"],
            phase_times=timings, backend=self.name,
            # t_* walls and *_ms counters accumulate unrounded (elim.py
            # t_add/_t_ms convention) and are rounded here at read
            # time, matching the tpu backend and bench.py so artifacts
            # stay diffable
            diagnostics={k_: (round(v, 3)
                              if (k_.startswith("t_")
                                  or k_.endswith("_ms"))
                              and isinstance(v, float)
                              else v if isinstance(v, (int, float))
                              else str(v))
                         for k_, v in {**out.get("build_stats", {}),
                                       **out.get("merge_stats", {})}.items()},
            tree={"parent": np.asarray(out["parent"]), "pos": out["pos"],
                  "deg": out["degrees"]} if opts.get("keep_tree") else None,
        )

    # -- incremental repartitioning (ISSUE 19) -----------------------------
    def _update_pipe(self, n: int, m: int) -> ShardedPipeline:
        """Cached fold pipeline for the resident update path, keyed on
        the pow2-quantized delta chunk width: repeat epochs at similar
        delta sizes reuse every compiled program (the sheeplint ``fold``
        rule's no-per-epoch-recompile contract). The simple per-segment
        dispatch (batch=1, inflight=1) is the right shape here — a delta
        is a handful of chunks, not a streamed epoch of thousands."""
        from sheep_tpu.ops import elim as elim_ops

        cs = elim_ops.pow2_at_least(min(m, self.chunk_edges),
                                    floor=1 << 10)
        cache = getattr(self, "_upd_pipes", None)
        if cache is None:
            cache = self._upd_pipes = {}
        pipe = cache.get((n, cs))
        if pipe is None:
            mesh = shards_mesh(self.n_devices)
            pipe = cache[(n, cs)] = cached_pipeline(
                n, cs, mesh, lift_levels=self.lift_levels,
                segment_rounds=self.segment_rounds,
                warm_schedule=self.warm_schedule,
                dispatch_batch=1, inflight=1, donate=False)
        return pipe

    def _fold_delta(self, state, edges) -> None:
        """Fold one epoch's adds into the carried table through the
        per-shard lockstep batch machinery: re-seed device row 0 with
        the converged table (merging is associative and idempotent —
        the checkpoint-resume idiom of ``ShardedPipeline.run``), fold
        the delta chunks round-robin over the mesh, butterfly-merge
        back. Bit-identical to the single-device fold: same constraint
        multiset under the same anchored order, unique fixpoint."""
        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        if not len(e):
            return
        n = state.n
        pipe = self._update_pipe(n, len(e))
        cs, rows = pipe.cs, pipe.n_local
        stats = state.stats
        order_sent = np.concatenate([state.order,
                                     np.asarray([n], np.int64)])
        pos_sent = np.concatenate([state.pos.astype(np.int32),
                                   np.asarray([n], np.int32)])
        fa = np.full((rows, n + 1), n, np.int32)
        if pipe.proc == 0:
            # vertex-space carried table -> position space, into global
            # row 0; the other rows start empty and merge away
            fa[0] = np.asarray(state.minp, np.int32)[order_sent]
        P_all = pipe._put(pipe.state_sharding, fa)
        pos = pipe.put_replicated(pos_sent)
        from sheep_tpu.backends.tpu_backend import pad_chunk

        chunks = [pad_chunk(e[off: off + cs], cs, n)
                  for off in range(0, len(e), cs)]
        sentinel = None
        for g0 in range(0, len(chunks), rows):
            group = chunks[g0: g0 + rows]
            if len(group) < rows:
                if sentinel is None:
                    sentinel = np.full((cs, 2), n, np.int32)
                group = group + [sentinel] * (rows - len(group))
            P_all = pipe.build_step(
                P_all, pipe.put_batch(np.stack(group)), pos,
                stats=stats)
        merged = pipe.merge(P_all, stats=stats)
        state.minp = np.asarray(  # sheeplint: sync-ok
            pipe.to_minp(merged, pos))
        stats["update_folds"] = stats.get("update_folds", 0) + 1

    def _move_rescore(self, src, dst, prevs, news, masks):
        """Distributed rescore hook for the incremental score cache
        (:func:`sheep_tpu.ops.score.move_rescore_sharded`): per-shard
        cut deltas for every k in ONE program, all-reduced once."""
        from sheep_tpu.ops.score import move_rescore_sharded

        return move_rescore_sharded(src, dst, prevs, news, masks,
                                    shards_mesh(self.n_devices))
