"""Multi-device sharded TPU backend, registered as ``tpu-sharded``.

The v5e-8 / multi-host execution strategy (SURVEY.md §2 #9, §7 step 5):
edge chunks round-robin across the ``shards`` mesh axis, per-device partial
forests, butterfly merge over ICI, psum scoring. Thin wrapper around
``ShardedPipeline.run`` (the single implementation of the streaming
loops); falls back gracefully to a 1-device mesh with results identical to
the ``tpu`` backend.
"""

from __future__ import annotations

import numpy as np

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.parallel.pipeline import ShardedPipeline
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range


@register
class TpuShardedBackend(Partitioner):
    name = "tpu-sharded"
    supports_checkpoint = True
    supports_multidevice = True

    def __init__(self, chunk_edges: int = 1 << 22, lift_levels: int = 0,
                 alpha: float = 1.0, n_devices: int | None = None,
                 segment_rounds: int = 32, warm_schedule=((1, 8),),
                 dispatch_batch: int = 0, inflight: int = 0,
                 donate_buffers: bool | None = None):
        self.chunk_edges = chunk_edges
        self.lift_levels = lift_levels
        self.alpha = alpha
        self.n_devices = n_devices
        self.segment_rounds = segment_rounds
        self.warm_schedule = tuple(warm_schedule)
        # batched segment dispatch (see ShardedPipeline): 0 = auto
        # (per-segment on cpu-jax; HBM-model-sized N on accelerators),
        # 1 = per-segment, N > 1 = stage N sharded batches per program
        if dispatch_batch < 0:
            raise ValueError("dispatch_batch must be >= 0 (0 = auto)")
        self.dispatch_batch = dispatch_batch
        # asynchronous dispatch pipeline depth for the batched path
        # (see ShardedPipeline.build_step_batch): 0 = auto (2 on
        # accelerators, 1 = synchronous on cpu-jax)
        if inflight < 0:
            raise ValueError("inflight must be >= 0 (0 = auto)")
        self.inflight = inflight
        # donate per-device tables + staging blocks into the batched
        # executions (None = auto: on for the batched/pipelined path)
        self.donate_buffers = donate_buffers

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        # comm_volume defaults True like every other backend (VERDICT r1
        # weak #5 asked for consistency); pass False to skip the host-side
        # O(cut pairs) accumulator on huge runs
        if getattr(stream, "order_anchor", False):
            from sheep_tpu.types import UnsupportedGraphError

            raise UnsupportedGraphError(
                "delta: inputs (anchored-order streams) are single-"
                "device today; use --backend tpu or cpu")
        n = stream.num_vertices
        check_tpu_vertex_range(n, self.name)
        mesh = shards_mesh(self.n_devices)
        # shrink the chunk so small graphs don't pad (and compile) up to
        # the full default chunk shape; shared helper so the backends'
        # chunk sizing (and checkpoint fingerprints) cannot diverge
        cs = stream.clamp_chunk_edges(self.chunk_edges,
                                      parts=mesh.devices.size)
        from sheep_tpu.backends.tpu_backend import resolve_dispatch_batch, \
            resolve_inflight

        inflight = resolve_inflight(self.inflight)
        donate = True if self.donate_buffers is None else self.donate_buffers
        nb = resolve_dispatch_batch(self.dispatch_batch, n, cs,
                                    inflight=inflight, donate=donate)
        pipe = ShardedPipeline(n, cs, mesh, lift_levels=self.lift_levels,
                               segment_rounds=self.segment_rounds,
                               warm_schedule=self.warm_schedule,
                               dispatch_batch=nb, inflight=inflight,
                               donate=donate)

        timings: dict = {}
        out = pipe.run(stream, k, alpha=self.alpha, weights=weights,
                       comm_volume=comm_volume, timings=timings,
                       checkpointer=checkpointer, resume=resume)
        return PartitionResult(
            assignment=out["assignment"], k=k, edge_cut=out["edge_cut"],
            total_edges=out["total_edges"],
            cut_ratio=out["edge_cut"] / max(out["total_edges"], 1),
            balance=out["balance"], comm_volume=out["comm_volume"],
            phase_times=timings, backend=self.name,
            # t_* walls and *_ms counters accumulate unrounded (elim.py
            # t_add/_t_ms convention) and are rounded here at read
            # time, matching the tpu backend and bench.py so artifacts
            # stay diffable
            diagnostics={k_: (round(v, 3)
                              if (k_.startswith("t_")
                                  or k_.endswith("_ms"))
                              and isinstance(v, float)
                              else v if isinstance(v, (int, float))
                              else str(v))
                         for k_, v in {**out.get("build_stats", {}),
                                       **out.get("merge_stats", {})}.items()},
            tree={"parent": np.asarray(out["parent"]), "pos": out["pos"],
                  "deg": out["degrees"]} if opts.get("keep_tree") else None,
        )
