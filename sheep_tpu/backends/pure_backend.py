"""Pure-numpy backend: the executable spec, registered as ``pure``.

Slow (Python union-find loop) but dependency-free; used as the correctness
oracle in tests and as a fallback when the native library is not built.
Streams chunk-by-chunk with a carried parent array, so its memory profile
matches the real backends (O(V + chunk)).
"""

from __future__ import annotations

import time

import numpy as np

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.core import pure
from sheep_tpu.types import ElimTree, PartitionResult


@register
class PureBackend(Partitioner):
    name = "pure"

    def __init__(self, chunk_edges: int = 1 << 22, alpha: float = 1.0):
        self.chunk_edges = chunk_edges
        self.alpha = alpha

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, **opts) -> PartitionResult:
        if opts.get("checkpointer") is not None:
            raise ValueError(
                "the pure backend does not checkpoint; use cpu/tpu/tpu-sharded")
        t = {}
        t0 = time.perf_counter()
        n = stream.num_vertices
        deg = np.zeros(n, dtype=np.int64)
        for chunk in stream.chunks(self.chunk_edges):
            deg += pure.degrees(chunk, n)
        t["degrees"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        pos = pure.elimination_order(deg)
        t["sort"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        parent = None
        for chunk in stream.chunks(self.chunk_edges):
            parent = pure.build_elim_tree(chunk, pos, parent=parent).parent
        if parent is None:
            parent = np.full(n, -1, dtype=np.int64)
        tree = ElimTree(parent=parent, pos=pos, n=n)
        t["build"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        w = deg if weights == "degree" else None
        assignment = pure.tree_split(tree, k, w, alpha=self.alpha)
        t["split"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        cut = total = 0
        cv_pairs = []
        for chunk in stream.chunks(self.chunk_edges):
            c, tt, _, _ = pure.edge_cut_score(chunk, assignment, k, comm_volume=False)
            cut += c
            total += tt
            if comm_volume:
                cv_pairs.append(pure.cut_pairs(chunk, assignment, k))
        cv = (int(len(np.unique(np.concatenate(cv_pairs)))) if cv_pairs else 0) \
            if comm_volume else None
        balance = pure.part_balance(assignment, k, w)
        t["score"] = time.perf_counter() - t0

        return PartitionResult(
            assignment=assignment,
            k=k,
            edge_cut=cut,
            total_edges=total,
            cut_ratio=cut / max(total, 1),
            balance=balance,
            comm_volume=cv,
            phase_times=t,
            backend=self.name,
            tree={"parent": parent, "pos": pos, "deg": deg}
            if opts.get("keep_tree") else None,
        )
