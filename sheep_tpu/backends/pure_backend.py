"""Pure-numpy backend: the executable spec, registered as ``pure``.

Slow (Python union-find loop) but dependency-free; used as the correctness
oracle in tests and as a fallback when the native library is not built.
Streams chunk-by-chunk with a carried parent array, so its memory profile
matches the real backends (O(V + chunk)).
"""

from __future__ import annotations

import time

import numpy as np

from sheep_tpu import obs
from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.core import pure
from sheep_tpu.types import ElimTree, PartitionResult


@register
class PureBackend(Partitioner):
    name = "pure"
    supports_incremental = True

    def __init__(self, chunk_edges: int = 1 << 22, alpha: float = 1.0):
        self.chunk_edges = chunk_edges
        self.alpha = alpha

    def _fold_delta(self, state, edges) -> None:
        """Incremental fold (ISSUE 15): the oracle twin of the cpu/tpu
        hooks — continue the carried forest under the anchored order."""
        from sheep_tpu.incremental import (_minp_from_parent,
                                           _parent_from_minp)

        n = state.n
        parent = _parent_from_minp(state.minp, state.order, n)
        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        for off in range(0, len(e), self.chunk_edges):
            parent = pure.build_elim_tree(
                e[off: off + self.chunk_edges], state.pos,
                parent=parent).parent
        state.minp = _minp_from_parent(parent, state.pos, n)

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, **opts) -> PartitionResult:
        if opts.get("checkpointer") is not None:
            raise ValueError(
                "the pure backend does not checkpoint; use cpu/tpu/tpu-sharded")
        t = {}
        t0 = time.perf_counter()
        n = stream.num_vertices
        root_sp = obs.begin("partition", backend=self.name, k=int(k), n=int(n))
        m_cheap = stream.num_edges_cheap
        obs.progress(backend=self.name, k=int(k),
                     edges_total=m_cheap, phase="degrees", chunks_done=0)
        sp = obs.begin("degrees")
        deg = np.zeros(n, dtype=np.int64)
        idx = 0
        # anchored-order streams (delta: inputs): degrees come from the
        # base segment only — the delta-log order contract
        deg_chunks = stream.anchor_chunks(self.chunk_edges) \
            if getattr(stream, "order_anchor", False) \
            else stream.chunks(self.chunk_edges)
        for chunk in deg_chunks:
            deg += pure.degrees(chunk, n)
            idx += 1
            obs.chunk_progress(idx, self.chunk_edges, m_cheap)
        t["degrees"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("sort")
        pos = pure.elimination_order(deg)
        t["sort"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("build")
        obs.progress(phase="build", chunks_done=0, edges_done=0)
        parent = None
        idx = 0
        for chunk in stream.chunks(self.chunk_edges):
            parent = pure.build_elim_tree(chunk, pos, parent=parent).parent
            idx += 1
            obs.chunk_progress(idx, self.chunk_edges, m_cheap)
        if parent is None:
            parent = np.full(n, -1, dtype=np.int64)
        tree = ElimTree(parent=parent, pos=pos, n=n)
        t["build"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("split")
        w = deg if weights == "degree" else None
        assignment = pure.tree_split(tree, k, w, alpha=self.alpha)
        from sheep_tpu.ops.split import account_split

        account_split(assignment, k, w, self.alpha)
        t["split"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("score")
        obs.progress(phase="score", chunks_done=0, edges_done=0)
        cut = total = 0
        cv_pairs = []
        for chunk in stream.chunks(self.chunk_edges):
            c, tt, _, _ = pure.edge_cut_score(chunk, assignment, k, comm_volume=False)
            cut += c
            total += tt
            if comm_volume:
                cv_pairs.append(pure.cut_pairs(chunk, assignment, k))
        cv = (int(len(np.unique(np.concatenate(cv_pairs)))) if cv_pairs else 0) \
            if comm_volume else None
        balance = pure.part_balance(assignment, k, w)
        t["score"] = time.perf_counter() - t0
        sp.end()
        root_sp.end()

        return PartitionResult(
            assignment=assignment,
            k=k,
            edge_cut=cut,
            total_edges=total,
            cut_ratio=cut / max(total, 1),
            balance=balance,
            comm_volume=cv,
            phase_times=t,
            backend=self.name,
            tree={"parent": parent, "pos": pos, "deg": deg}
            if opts.get("keep_tree") else None,
        )
