"""Native C++ CPU backend, registered as ``cpu`` (SURVEY.md §2 #11).

The single-socket reference path: this is the denominator of the 10x
edges/sec north-star target and the edge-cut baseline for the <=2%
regression bound. Streams chunk-by-chunk through the C ABI in
sheep_tpu/core/csrc; O(V + chunk) memory.
"""

from __future__ import annotations

import time

import numpy as np

from sheep_tpu import obs
from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.core import native, pure
from sheep_tpu.types import PartitionResult

if not native.available():  # pragma: no cover - toolchain missing
    raise ImportError("native sheep_core library unavailable")


@register
class CpuBackend(Partitioner):
    name = "cpu"
    supports_checkpoint = True
    supports_incremental = True

    def __init__(self, chunk_edges: int = 1 << 22, alpha: float = 1.0):
        self.chunk_edges = chunk_edges
        self.alpha = alpha

    def _fold_delta(self, state, edges) -> None:
        """Incremental fold (ISSUE 15): extend the converged carried
        forest with a delta batch under the state's ANCHORED order —
        exactly the streaming build's carried-parent continuation, so
        the result is the unique fixpoint of the grown multiset."""
        from sheep_tpu.incremental import (_minp_from_parent,
                                           _parent_from_minp)

        n = state.n
        parent = _parent_from_minp(state.minp, state.order, n)
        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        for off in range(0, len(e), self.chunk_edges):
            parent = native.build_elim_tree(
                e[off: off + self.chunk_edges], state.pos,
                parent=parent)
        state.minp = _minp_from_parent(parent, state.pos, n)

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        from sheep_tpu.utils import checkpoint as ckpt
        from sheep_tpu.utils.fault import maybe_fail

        t = {}
        t0 = time.perf_counter()
        n = stream.num_vertices
        root_sp = obs.begin("partition", backend=self.name, k=int(k),
                            n=int(n))
        m_cheap = stream.num_edges_cheap
        obs.progress(backend=self.name, k=int(k), edges_total=m_cheap)
        meta = ckpt.stream_meta(stream, k, self.chunk_edges, weights=weights,
                                alpha=self.alpha, comm_volume=comm_volume,
                                state_format="parent")
        state = ckpt.resume_state(checkpointer, meta, resume)
        from_phase = ckpt.phase_index(state.phase) if state else 0

        if state:
            deg = state.arrays["deg"].copy()
        else:
            deg = np.zeros(n, dtype=np.int64)
        sp = obs.begin("degrees")
        obs.progress(phase="degrees", chunks_done=0, edges_done=0)
        # anchored-order streams (delta: inputs, io/deltalog.py): the
        # elimination order derives from the BASE segment's degrees —
        # the contract that makes the incremental path bit-identical
        # to this one-shot build; build/score still stream the full
        # surviving multiset
        anchored = bool(getattr(stream, "order_anchor", False))
        if from_phase == 0:
            start = state.chunk_idx if state else 0
            idx = start
            deg_chunks = stream.anchor_chunks(
                self.chunk_edges, start_chunk=start) if anchored \
                else stream.chunks(self.chunk_edges, start_chunk=start)
            for chunk in deg_chunks:
                native.degrees(chunk, n, out=deg)
                idx += 1
                maybe_fail("degrees", idx - start)
                obs.chunk_progress(idx, self.chunk_edges, m_cheap)
                if checkpointer is not None and checkpointer.due(idx - start):
                    checkpointer.save("degrees", idx, {"deg": deg}, meta)
        t["degrees"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("sort")
        pos = native.elim_order(deg)
        t["sort"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("build")
        obs.progress(phase="build", chunks_done=0, edges_done=0)
        if state and from_phase >= 2:
            parent = state.arrays["parent"].copy()
        else:
            if state and state.phase == "build":
                parent = state.arrays["parent"].copy()
                start = state.chunk_idx
            else:
                parent = np.full(n, -1, dtype=np.int64)
                start = 0
            idx = start
            for chunk in stream.chunks(self.chunk_edges, start_chunk=start):
                native.build_elim_tree(chunk, pos, parent=parent)
                idx += 1
                maybe_fail("build", idx - start)
                obs.chunk_progress(idx, self.chunk_edges, m_cheap)
                if checkpointer is not None and checkpointer.due(idx - start):
                    checkpointer.save("build", idx,
                                      {"deg": deg, "parent": parent}, meta)
        t["build"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("split")
        w = deg.astype(np.float64) if weights == "degree" else None
        assignment = native.tree_split(parent, pos, k, weights=w, alpha=self.alpha)
        from sheep_tpu.ops.split import account_split

        account_split(assignment, k, w, self.alpha)
        t["split"] = time.perf_counter() - t0
        sp.end()

        t0 = time.perf_counter()
        sp = obs.begin("score")
        obs.progress(phase="score", chunks_done=0, edges_done=0)
        cut = total = 0
        cv_parts = []
        start = 0
        if state and state.phase == "score":
            start = state.chunk_idx
            cut = int(state.arrays["cut"])
            total = int(state.arrays["total"])
            if comm_volume:
                cv_parts.append(state.arrays["cv_keys"])
        idx = start
        for chunk in stream.chunks(self.chunk_edges, start_chunk=start):
            c, tt = native.score_chunk(chunk, assignment, n)
            cut += c
            total += tt
            if comm_volume:
                cv_parts.append(native.cut_pairs(chunk, assignment, n, k))
            idx += 1
            maybe_fail("score", idx - start)
            obs.chunk_progress(idx, self.chunk_edges, m_cheap)
            if checkpointer is not None and checkpointer.due(idx - start):
                cv_parts = ckpt.save_score_state(
                    checkpointer, idx, cut, total, cv_parts,
                    {"deg": deg, "parent": parent}, meta, comm_volume)
        cv = int(len(ckpt.compact_cv_keys(cv_parts))) if comm_volume else None
        balance = pure.part_balance(assignment, k, deg if weights == "degree" else None)
        t["score"] = time.perf_counter() - t0
        sp.end()
        root_sp.end()
        if checkpointer is not None:
            checkpointer.clear()

        return PartitionResult(
            assignment=assignment, k=k, edge_cut=cut, total_edges=total,
            cut_ratio=cut / max(total, 1), balance=balance,
            comm_volume=cv if comm_volume else None,
            phase_times=t, backend=self.name,
            tree={"parent": parent, "pos": pos, "deg": deg}
            if opts.get("keep_tree") else None,
        )
