"""Vertex-sharded big-V backend, registered as ``tpu-bigv``.

For graphs whose vertex tables exceed one chip's HBM (BASELINE.md eval
config 5, RMAT-30 class): every vertex-indexed table (pos/order/minp,
degrees, assignment) is block-sharded over the device mesh and the
displacement fixpoint runs as ONE distributed forest with routed
collectives (``parallel/bigv.py``). Per-device table memory is O(V/D);
the standard ``tpu-sharded`` backend is faster whenever the replicated
tables fit (V <= 2^29 single-chip), so pick this one only beyond that.
Multi-host works the same way (the mesh spans all processes' devices and
the routed collectives ride DCN); tested against the sequential oracle in
``tests/test_multihost.py``.
"""

from __future__ import annotations

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.parallel.bigv import BigVPipeline
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range


@register
class TpuBigVBackend(Partitioner):
    name = "tpu-bigv"
    supports_multidevice = True
    supports_checkpoint = True

    def __init__(self, chunk_edges: int = 1 << 20, alpha: float = 1.0,
                 jumps: int = 128, n_devices: int | None = None,
                 lift_levels: int = 0, segment_rounds: int = 16,
                 hoist_bytes: int | None = None):
        self.chunk_edges = chunk_edges
        self.alpha = alpha
        self.jumps = jumps
        self.n_devices = n_devices
        # memory/speed trade of the routed fixpoint: each lifting level
        # is a (D, B)-shaped routed lookup inside one compiled program
        # (auto depth at V=2^30 OOM-killed a 125 GB virtual-mesh host —
        # tools/bigv_scale30.py), and segment_rounds bounds rounds per
        # device execution the same way. 0 = auto depth.
        self.lift_levels = lift_levels
        self.segment_rounds = segment_rounds
        # per-device HBM budget for the per-segment (stale) lifting
        # stack; default 0 = per-round squaring — hoisting measured
        # WORSE below the V-dominant regime (see BigVPipeline)
        self.hoist_bytes = hoist_bytes

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        if getattr(stream, "order_anchor", False):
            from sheep_tpu.types import UnsupportedGraphError

            raise UnsupportedGraphError(
                "delta: inputs (anchored-order streams) are single-"
                "device today; use --backend tpu or cpu")
        n = stream.num_vertices
        check_tpu_vertex_range(n, self.name)
        mesh = shards_mesh(self.n_devices)
        cs = self.chunk_edges
        m_cheap = stream.num_edges_cheap
        if m_cheap is not None:
            cs = min(cs, max(1024, -(-m_cheap // mesh.devices.size)))
        pipe = BigVPipeline(n, cs, mesh, jumps=self.jumps,
                            lift_levels=self.lift_levels,
                            segment_rounds=self.segment_rounds,
                            hoist_bytes=self.hoist_bytes)

        timings: dict = {}
        out = pipe.run(stream, k, alpha=self.alpha, weights=weights,
                       comm_volume=comm_volume, timings=timings,
                       checkpointer=checkpointer, resume=resume)
        return PartitionResult(
            assignment=out["assignment"], k=k, edge_cut=out["edge_cut"],
            total_edges=out["total_edges"],
            cut_ratio=out["edge_cut"] / max(out["total_edges"], 1),
            balance=out["balance"], comm_volume=out["comm_volume"],
            phase_times=timings, backend=self.name,
            diagnostics={"fixpoint_rounds": float(out["fixpoint_rounds"]),
                         # the clamped value actually run, so artifact
                         # tooling records it instead of re-deriving the
                         # clamp formula (which could silently drift)
                         "chunk_edges_effective": float(cs),
                         **{k_: float(v) for k_, v in
                            out.get("build_stats", {}).items()}},
            tree={"parent": out["parent"], "pos": out["pos"],
                  "deg": out["degrees"]} if opts.get("keep_tree") else None,
        )
