"""Vertex-sharded big-V backend, registered as ``tpu-bigv``.

For graphs whose vertex tables exceed one chip's HBM (BASELINE.md eval
config 5, RMAT-30 class): every vertex-indexed table (pos/order/minp,
degrees, assignment) is block-sharded over the device mesh and the
displacement fixpoint runs as ONE distributed forest with routed
collectives (``parallel/bigv.py``). Per-device table memory is O(V/D);
the standard ``tpu-sharded`` backend is faster whenever the replicated
tables fit (V <= 2^29 single-chip), so pick this one only beyond that.
Multi-host works the same way (the mesh spans all processes' devices and
the routed collectives ride DCN); tested against the sequential oracle in
``tests/test_multihost.py``.
"""

from __future__ import annotations

from sheep_tpu.backends.base import Partitioner, register
from sheep_tpu.parallel.bigv import BigVPipeline, cached_pipeline
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range


@register
class TpuBigVBackend(Partitioner):
    name = "tpu-bigv"
    supports_multidevice = True
    supports_checkpoint = True
    # incremental repartitioning (ISSUE 19): delta epochs fold into the
    # one distributed forest (_fold_delta), scored refreshes rescore
    # device-side with one all-reduce (_move_rescore)
    supports_incremental = True

    def __init__(self, chunk_edges: int = 1 << 20, alpha: float = 1.0,
                 jumps: int = 128, n_devices: int | None = None,
                 lift_levels: int = 0, segment_rounds: int = 16,
                 hoist_bytes: int | None = None):
        self.chunk_edges = chunk_edges
        self.alpha = alpha
        self.jumps = jumps
        self.n_devices = n_devices
        # memory/speed trade of the routed fixpoint: each lifting level
        # is a (D, B)-shaped routed lookup inside one compiled program
        # (auto depth at V=2^30 OOM-killed a 125 GB virtual-mesh host —
        # tools/bigv_scale30.py), and segment_rounds bounds rounds per
        # device execution the same way. 0 = auto depth.
        self.lift_levels = lift_levels
        self.segment_rounds = segment_rounds
        # per-device HBM budget for the per-segment (stale) lifting
        # stack; default 0 = per-round squaring — hoisting measured
        # WORSE below the V-dominant regime (see BigVPipeline)
        self.hoist_bytes = hoist_bytes

    def partition(self, stream, k: int, weights: str = "unit",
                  comm_volume: bool = True, checkpointer=None,
                  resume: bool = False, **opts) -> PartitionResult:
        if getattr(stream, "order_anchor", False):
            import jax

            if jax.process_count() > 1:
                from sheep_tpu.types import UnsupportedGraphError

                raise UnsupportedGraphError(
                    "delta: inputs stream single-shard; a multi-host "
                    "mesh cannot byte-range an anchored log — run the "
                    "delta build on a single-host mesh or --backend "
                    "tpu/cpu")
        n = stream.num_vertices
        check_tpu_vertex_range(n, self.name)
        mesh = shards_mesh(self.n_devices)
        cs = self.chunk_edges
        m_cheap = stream.num_edges_cheap
        if m_cheap is not None:
            cs = min(cs, max(1024, -(-m_cheap // mesh.devices.size)))
        pipe = cached_pipeline(n, cs, mesh, jumps=self.jumps,
                               lift_levels=self.lift_levels,
                               segment_rounds=self.segment_rounds,
                               hoist_bytes=self.hoist_bytes)

        timings: dict = {}
        out = pipe.run(stream, k, alpha=self.alpha, weights=weights,
                       comm_volume=comm_volume, timings=timings,
                       checkpointer=checkpointer, resume=resume)
        return PartitionResult(
            assignment=out["assignment"], k=k, edge_cut=out["edge_cut"],
            total_edges=out["total_edges"],
            cut_ratio=out["edge_cut"] / max(out["total_edges"], 1),
            balance=out["balance"], comm_volume=out["comm_volume"],
            phase_times=timings, backend=self.name,
            diagnostics={"fixpoint_rounds": float(out["fixpoint_rounds"]),
                         # the clamped value actually run, so artifact
                         # tooling records it instead of re-deriving the
                         # clamp formula (which could silently drift)
                         "chunk_edges_effective": float(cs),
                         **{k_: float(v) for k_, v in
                            out.get("build_stats", {}).items()}},
            tree={"parent": out["parent"], "pos": out["pos"],
                  "deg": out["degrees"]} if opts.get("keep_tree") else None,
        )

    # -- incremental repartitioning (ISSUE 19) -----------------------------
    def _update_pipe(self, n: int, m: int) -> BigVPipeline:
        """Cached fold pipeline for the resident update path, keyed on
        the pow2-quantized delta chunk width so repeat epochs reuse
        every compiled routed-collective program (the sheeplint ``fold``
        rule's no-per-epoch-recompile contract)."""
        from sheep_tpu.ops.elim import pow2_at_least

        cs = pow2_at_least(min(m, self.chunk_edges), floor=1 << 10)
        cache = getattr(self, "_upd_pipes", None)
        if cache is None:
            cache = self._upd_pipes = {}
        pipe = cache.get((n, cs))
        if pipe is None:
            mesh = shards_mesh(self.n_devices)
            pipe = cache[(n, cs)] = cached_pipeline(
                n, cs, mesh, jumps=self.jumps,
                lift_levels=self.lift_levels,
                segment_rounds=self.segment_rounds,
                hoist_bytes=self.hoist_bytes)
        return pipe

    def _fold_delta(self, state, edges) -> None:
        """Fold one epoch's adds into the ONE distributed forest: the
        carried vertex-space table re-enters block-sharded in position
        space, the delta chunks fold through the routed segment
        machinery, and the converged table gathers back. Bit-identical
        to the single-device fold: same constraint multiset under the
        same anchored order, unique fixpoint."""
        import numpy as np

        e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        if not len(e):
            return
        n = state.n
        pipe = self._update_pipe(n, len(e))
        cs, rows = pipe.cs, pipe.n_local
        stats = state.stats
        order_sent = np.concatenate([state.order,
                                     np.asarray([n], np.int64)])
        pos_pad = np.concatenate([state.pos.astype(np.int32),
                                  np.asarray([n], np.int32)])
        pos_sh = pipe._shard_table(pos_pad)
        P_sh = pipe._shard_table(
            np.asarray(state.minp, np.int32)[order_sent])
        from sheep_tpu.backends.tpu_backend import pad_chunk

        chunks = [pad_chunk(e[off: off + cs], cs, n)
                  for off in range(0, len(e), cs)]
        sentinel = None
        total_rounds = 0
        for g0 in range(0, len(chunks), rows):
            group = chunks[g0: g0 + rows]
            if len(group) < rows:
                if sentinel is None:
                    sentinel = np.full((cs, 2), n, np.int32)
                group = group + [sentinel] * (rows - len(group))
            P_sh, rounds = pipe.build_step(
                P_sh, pos_sh, pipe._put(pipe.batch_sharding,
                                        np.stack(group)),
                stats=stats)
            total_rounds += int(rounds)
        P_host = pipe._allgather_table(pipe._local_block(P_sh))[:n + 1]
        state.minp = P_host[pos_pad]
        stats["update_folds"] = stats.get("update_folds", 0) + 1
        stats["update_rounds"] = \
            stats.get("update_rounds", 0) + total_rounds

    def _move_rescore(self, src, dst, prevs, news, masks):
        """Distributed rescore hook for the incremental score cache
        (:func:`sheep_tpu.ops.score.move_rescore_sharded`): per-shard
        cut deltas for every k in ONE program, all-reduced once."""
        from sheep_tpu.ops.score import move_rescore_sharded

        return move_rescore_sharded(src, dst, prevs, news, masks,
                                    shards_mesh(self.n_devices))
