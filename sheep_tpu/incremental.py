"""Incremental repartitioning for mutating graphs (ISSUE 15 tentpole).

The elimination fixpoint is order-independent in the constraint
multiset (the PR-1/PR-3 invariant every pipeline PR leans on), so a
*converged* carried table absorbs a new-edge batch as just another
segment batch: O(Δ) device work instead of an O(E) rebuild. This
module is the state + driver around that observation:

:class:`PartitionState`
    A resident partition: the anchored elimination order, the
    converged carried table (vertex-space ``minp``), the anchor
    degree table, the applied delta history (adds + tombstones) and
    the epoch counter. O(V + Δ) host memory; the base graph is never
    re-materialized.

:func:`begin_incremental` / :func:`state_from_build`
    Create a state from a fresh build (``keep_tree=True`` products)
    or from the served engine's build artifacts.

``backend.partition_update(state, adds, deletes)``
    The first-class backend capability (``supports_incremental`` on
    ``backends/base.py``): fold an epoch's adds into the carried
    table via the backend's ``_fold_delta`` hook (the tpu hook runs
    the existing batched dispatch of ``ops/elim.py``), tombstone its
    deletes, bump the epoch, auto-compact past the staleness
    threshold, and (optionally) re-split + re-score.

**Exactness contract** (tests/test_incremental.py):

- *Adds* are EXACT: after folding epochs 1..N, the resident table is
  bit-identical to a one-shot build of the ``delta:`` input at epoch
  N — same anchored order (the delta-log format's documented
  semantics, :mod:`sheep_tpu.io.deltalog`), same constraint multiset,
  unique fixpoint. The shuffled two-halves replay pins this on the
  pure/cpu/tpu backends and through the served ``update`` verb.
- *Deletes* tombstone (an elimination forest does not un-fold); the
  partition keeps serving with the stale tree until **compaction**.
  Full compaction is a clean rebuild of the surviving multiset with
  RE-ANCHORED (fresh survivor-degree) order — bit-identical to a
  from-scratch build of the survivors, by construction. Subtree
  compaction keeps the anchored order and rebuilds only the
  tree-split parts the tombstones touch (``tree_split`` locality) —
  an explicitly score-bounded approximation, gated in tests.
- *Order drift*: the anchored order ages as degrees drift; the cut
  cost of anchoring is bounded in tests and in the quality gate's
  dynamic-graph scenario (tools/quality_regress.py), and compaction
  re-anchors.

A staleness counter (``stale_deletes`` vs ``compact_threshold``,
default 20% of the surviving edges) forces compaction inside
:func:`apply_update` so a delete-heavy stream cannot ride a stale
tree forever.

**Incremental scoring** (ISSUE 17 tentpole): a scored epoch no longer
pays an O(E) survivor pass. The first scored :func:`refresh` seeds a
score cache — a symmetrized, mmap-backed adjacency index of the base
(:class:`_SurvivorIndex`, built once via the ``io/csr.py`` machinery)
plus per-k (cut, total) accumulators and the assignments they were
scored under. Each :func:`apply_update` then folds the delta's exact
effect into the accumulators under the cached assignments (O(Δ),
:func:`sheep_tpu.ops.score.edge_effect_host`), and :func:`refresh`
rescores ONLY the arcs incident to vertices whose assignment changed
in the refold (:func:`sheep_tpu.ops.refine.move_rescore_host`) —
bit-equal to the full ``score_stream`` pass by construction, pinned
in tests, and cross-checked at runtime when ``SHEEP_SCORE_AUDIT=1``
(the audit runs the O(E) pass too and raises on ANY divergence).
``comm_volume=True`` refreshes keep the full pass (distinct-pair
counting is not incrementally maintainable) and re-seed the cache.
:func:`rebase_state` (full compaction + base rewrite) drops the cache;
the next scored refresh re-seeds it over the fresh base.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import List, Optional

import numpy as np

from sheep_tpu import obs

NO_PARENT = -1


def _parent_from_minp(minp: np.ndarray, order: np.ndarray,
                      n: int) -> np.ndarray:
    """Vertex-space minp (int32[n+1], n = none) -> parent int64[n]."""
    m = np.asarray(minp[:n])
    has = m < n
    parent = np.full(n, NO_PARENT, dtype=np.int64)
    parent[has] = order[m[has]]
    return parent


def _minp_from_parent(parent: np.ndarray, pos: np.ndarray,
                      n: int) -> np.ndarray:
    minp = np.full(n + 1, n, dtype=np.int32)
    has = parent >= 0
    minp[:n][has] = pos[parent[has]]
    return minp


@dataclasses.dataclass
class PartitionState:
    """One resident partition (see module docstring)."""

    n: int
    ks: List[int]
    weights: str
    alpha: float
    chunk_edges: int
    backend_name: str
    pos: np.ndarray            # int64[n], anchored elimination order
    deg_anchor: np.ndarray     # int64[n], degrees the order anchors to
    minp: np.ndarray           # int32[n+1], converged carried table
    total_edges: int           # surviving multiset size
    base: object = None        # re-openable base stream
    base_spec: Optional[str] = None
    epoch: int = 0
    anchored_at_epoch: int = 0
    adds: List[np.ndarray] = dataclasses.field(default_factory=list)
    tombs: List[np.ndarray] = dataclasses.field(default_factory=list)
    # tombstones since the last compaction: the dirty set subtree
    # compaction localizes on, and the staleness numerator
    pending_tombs: List[np.ndarray] = dataclasses.field(
        default_factory=list)
    stale_deletes: int = 0
    compactions: int = 0
    compact_threshold: Optional[int] = None  # None = 20% of survivors
    stats: dict = dataclasses.field(default_factory=dict)
    _order: Optional[np.ndarray] = None
    # incremental score cache (ISSUE 17): seeded by the first scored
    # refresh, never serialized — a reloaded snapshot re-seeds with one
    # full pass. See _seed_score_cache for the layout.
    _score: Optional[dict] = None

    @property
    def order(self) -> np.ndarray:
        """order[p] = vertex at rank p (inverse of pos), cached."""
        if self._order is None or len(self._order) != self.n:
            order = np.empty(self.n, dtype=np.int64)
            order[self.pos] = np.arange(self.n, dtype=np.int64)
            self._order = order
        return self._order

    def tomb_array(self, pending_only: bool = False) -> np.ndarray:
        src = self.pending_tombs if pending_only else self.tombs
        if not src:
            return np.zeros((0, 2), np.int64)
        return np.concatenate(src, axis=0)

    def adds_array(self) -> np.ndarray:
        if not self.adds:
            return np.zeros((0, 2), np.int64)
        return np.concatenate(self.adds, axis=0)

    def resolved_compact_threshold(self) -> int:
        if self.compact_threshold is not None:
            return int(self.compact_threshold)
        return max(1024, int(self.total_edges) // 5)

    def survivor_stream(self):
        """EdgeStream view of the CURRENT surviving multiset (base
        filtered by tombstones + applied adds) — what scoring and
        full compaction stream. O(Δ) host state, base re-streamed."""
        from sheep_tpu.io.deltalog import filter_tombstones
        from sheep_tpu.io.edgestream import EdgeStream

        state = self

        def factory():
            cs = state.chunk_edges
            # state.tombs holds BASE tombstones only — deletes were
            # resolved against pending adds at apply time
            # (deltalog.cancel_adds), so the filter must never touch
            # the adds: a base tombstone reaching forward into a
            # later-epoch add would diverge from the one-shot replay
            yield from filter_tombstones(state.base.chunks(cs),
                                         state.tomb_array())
            for a in state.adds:
                for off in range(0, len(a), cs):
                    yield a[off: off + cs]

        return EdgeStream.from_generator(
            factory, n_vertices=self.n,
            num_edges=max(0, int(self.total_edges)))


def state_from_build(stream, ks, weights: str, alpha: float,
                     chunk_edges: int, backend_name: str,
                     pos, deg, minp, total_edges: int,
                     base_spec: Optional[str] = None) -> PartitionState:
    """Wrap a finished build's artifacts into a resident state. When
    the build's input was a ``delta:`` stream, its applied log (adds /
    tombstones / epoch) seeds the state so the resident partition and
    the one-shot build describe the same multiset."""
    n = int(stream.num_vertices)
    pos = np.asarray(pos, dtype=np.int64)[:n]
    deg_anchor = np.asarray(deg, dtype=np.int64)[:n].copy()
    st = PartitionState(
        n=n, ks=[int(k) for k in ks], weights=str(weights),
        alpha=float(alpha), chunk_edges=int(chunk_edges),
        backend_name=str(backend_name), pos=pos,
        deg_anchor=deg_anchor,
        minp=np.asarray(minp, dtype=np.int32),
        total_edges=int(total_edges), base=stream,
        base_spec=base_spec)
    if getattr(stream, "order_anchor", False):
        # delta: input — the base is the anchor segment; the log's
        # surviving adds/tombstones are already folded/filtered into
        # the build, so the state starts at the log's epoch
        st.base = stream.base
        st.base_spec = getattr(stream, "base_spec", base_spec)
        if len(stream.adds):
            st.adds = [np.asarray(stream.adds, np.int64)]
        if len(stream.tombs):
            st.tombs = [np.asarray(stream.tombs, np.int64)]
        st.epoch = int(stream.epoch)
    if st.base_spec is None:
        # a path-backed stream is its own re-openable spec (snapshot
        # reload re-opens it); pure in-memory bases stay None and
        # load_state then needs the stream handed back explicitly
        st.base_spec = getattr(st.base, "path", None)
    return st


def begin_incremental(input_or_stream, ks, backend=None, weights: str = "unit",
          alpha: float = 1.0, comm_volume: bool = False, **opts):
    """Build the base partition and return ``(state, result)`` —
    the entry point of the incremental lifecycle. ``input_or_stream``
    accepts everything :func:`sheep_tpu.io.edgestream.open_input`
    does, including ``delta:`` specs (the state then resumes at the
    log's last epoch)."""
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io.edgestream import open_input

    if isinstance(ks, int):
        ks = [ks]
    ks = [int(k) for k in ks]
    base_spec = None
    if isinstance(input_or_stream, (str, os.PathLike)):
        base_spec = os.fspath(input_or_stream)
        stream = open_input(base_spec)
    else:
        stream = input_or_stream
    if backend is None or isinstance(backend, str):
        from sheep_tpu import list_backends

        name = backend
        if name is None:
            avail = list_backends()
            name = next(b for b in ("tpu", "cpu", "pure")
                        if b in avail)
        be = get_backend(name, **opts)
    else:
        be = backend
    if not getattr(be, "supports_incremental", False):
        raise ValueError(f"backend {be.name!r} does not support "
                         f"incremental updates (supports_incremental)")
    res = be.partition(stream, ks[0], weights=weights,
                       comm_volume=comm_volume, keep_tree=True)
    tree = res.tree
    n = int(stream.num_vertices)
    minp = _minp_from_parent(np.asarray(tree["parent"], np.int64),
                             np.asarray(tree["pos"], np.int64), n)
    state = state_from_build(
        stream, ks, weights, alpha, getattr(be, "chunk_edges", 1 << 22),
        be.name, tree["pos"], tree["deg"], minp, res.total_edges,
        base_spec=base_spec)
    state.alpha = float(getattr(be, "alpha", alpha))
    return state, res


def _validate_delta(edges, n: int, what: str) -> np.ndarray:
    e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if len(e) and (e.min() < 0 or e.max() >= n):
        raise ValueError(
            f"delta {what} reference vertex {int(e.max())} outside the "
            f"resident vertex space [0, {n}); build the base with "
            f"--num-vertices headroom to admit new vertices")
    return e


# ----------------------------------------------------------------------
# incremental scoring (ISSUE 17 tentpole): survivor adjacency index +
# per-k cut/total accumulators, exactly equal to the full score pass
# ----------------------------------------------------------------------


class _SurvivorIndex:
    """Symmetrized, mmap-backed adjacency of the resident BASE stream
    (the ``io/csr.py`` machinery): each base edge contributes both
    arcs, so ``arcs_from(changed)`` enumerates every base occurrence
    touching a changed vertex — once per direction — without streaming
    E edges. Built once per base (one extra two-pass conversion at
    cache-seed time), shared across ks, dropped with the state; the
    add/tombstone overlay lives on the score cache — the index file
    itself never mutates. A self-loop contributes two ``u -> u`` arcs,
    so the undirected base multiplicity of {a, b} is the count of b in
    a's arc list (halved when a == b)."""

    def __init__(self, state: PartitionState):
        import tempfile
        import weakref

        from sheep_tpu.io import csr as csr_mod
        from sheep_tpu.io.edgestream import EdgeStream

        base = state.base
        cs = state.chunk_edges

        def factory():
            for chunk in base.chunks(cs):
                e = np.asarray(chunk, np.int64).reshape(-1, 2)
                if len(e):
                    yield np.concatenate([e, e[:, ::-1]], axis=0)

        fd, path = tempfile.mkstemp(prefix="sheep_symadj_",
                                    suffix=".csr")
        os.close(fd)
        csr_mod.write_csr(path, EdgeStream.from_generator(
            factory, n_vertices=state.n), n_vertices=state.n)
        self.path = path
        self.csr = csr_mod.CsrGraph(path)
        self._finalizer = weakref.finalize(
            self, _SurvivorIndex._cleanup, self.csr, path)

    @staticmethod
    def _cleanup(csr, path: str) -> None:
        try:
            csr.close()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        try:
            os.unlink(path)
        except OSError:
            pass

    def drop(self) -> None:
        self._finalizer()

    def multiplicity(self, a: int, b: int) -> int:
        """Base multiset count of the undirected key {a, b}."""
        nb = self.csr.neighbors(a)
        c = int(np.count_nonzero(nb == b))
        return c // 2 if a == b else c


def _drop_score_cache(state: PartitionState) -> None:
    sc = state._score
    if sc is None:
        return
    idx = sc.get("index")
    if idx is not None:
        idx.drop()
    state._score = None


def _seed_score_cache(state: PartitionState, assigns: dict,
                      scored: dict) -> None:
    """(Re)seed the score cache from a just-completed FULL pass.

    Layout: ``index`` (symmetrized base CSR), ``fired`` (normalized
    non-self tombstone key -> occurrences actually removed from the
    base, capped at base multiplicity — unmatched tombstones never
    fire, matching deltalog.filter_tombstones), ``ov`` (symmetrized
    arc chunks of the pending adds, or None to lazily rebuild from
    ``state.adds``), ``prev`` / ``cut`` / ``total`` (the assignments
    the accumulators are exact under). Any failure to build the index
    leaves the cache unset — every later refresh just stays on the
    full pass; the cache is an optimization, never a requirement."""
    sc = state._score
    if sc is None:
        try:
            index = _SurvivorIndex(state)
        except Exception:  # noqa: BLE001 — fall back to full passes
            state._score = None
            return
        fired: dict = {}
        for a, b in state.tomb_array():
            a, b = int(a), int(b)
            if a == b:
                continue  # self-loops never score (total excludes them)
            key = (a, b) if a < b else (b, a)
            f = fired.get(key, 0)
            if f < index.multiplicity(a, b):
                fired[key] = f + 1
        sc = state._score = {"index": index, "fired": fired,
                             "ov": None, "ov_adds": -1}
    sc["prev"] = {k: np.array(a, copy=True)
                  for k, a in assigns.items()}
    sc["cut"] = {k: int(scored[k][0]) for k in assigns}
    sc["total"] = int(next(iter(scored.values()))[1])


def _account_adds(state: PartitionState, adds: np.ndarray) -> None:
    """O(Δ) accumulator fold of an add batch under the CACHED
    assignments; called right after ``state.adds.append(adds)``."""
    sc = state._score
    if sc is None or "prev" not in sc:
        return
    from sheep_tpu.ops.score import edge_effect_host

    valid, cuts = edge_effect_host(adds, sc["prev"], state.n)
    sc["total"] += valid
    for k, c in cuts.items():
        sc["cut"][k] += c
    if sc.get("ov") is not None \
            and sc.get("ov_adds") == len(state.adds) - 1:
        sc["ov"].append(np.concatenate([adds, adds[:, ::-1]], axis=0))
        sc["ov_adds"] = len(state.adds)
    else:
        sc["ov"] = None  # overlay stale — rebuilt at next rescore


def _account_dels(state: PartitionState, dels: np.ndarray,
                  base_tombs: np.ndarray) -> None:
    """O(Δ) accumulator fold of a delete batch, called right after
    ``cancel_adds`` resolved it: deletes that cancelled a pending add
    remove an edge with the SAME endpoints (cancel_adds matches on the
    undirected key), and a base tombstone removes one base occurrence
    only while the base multiplicity is not exhausted — the exact
    multiset algebra of ``filter_tombstones``, answered in O(deg) from
    the symmetrized index instead of a stream pass."""
    sc = state._score
    if sc is None or "prev" not in sc:
        return
    from sheep_tpu.ops.score import edge_effect_host

    prev, n = sc["prev"], state.n
    dv, dc = edge_effect_host(dels, prev, n)
    bv, bc = edge_effect_host(base_tombs, prev, n)
    # the add-cancelled portion = dels minus the base-resolved remainder
    sc["total"] -= dv - bv
    for k in dc:
        sc["cut"][k] -= dc[k] - bc[k]
    fired, idx = sc["fired"], sc["index"]
    for a, b in np.asarray(base_tombs, np.int64).reshape(-1, 2):
        a, b = int(a), int(b)
        if a == b:
            continue  # self-loops never score
        key = (a, b) if a < b else (b, a)
        f = fired.get(key, 0)
        if f < idx.multiplicity(a, b):
            fired[key] = f + 1
            sc["total"] -= 1
            for k, p in prev.items():
                if p[a] != p[b]:
                    sc["cut"][k] -= 1
    sc["ov"] = None  # cancel_adds rewrote state.adds


def _drop_fired_arcs(src: np.ndarray, dst: np.ndarray, fired: dict,
                     n: int) -> tuple:
    """Remove the fired-tombstone occurrences from a base arc gather:
    per ordered pair, the first ``fired`` occurrences are dropped —
    occurrences of one pair are interchangeable for scoring, so WHICH
    ones go is immaterial. O(A) for the key probe plus O(H log H) over
    the arcs actually hitting a deleted key."""
    rem: dict = {}
    for (a, b), c in fired.items():
        rem[a * n + b] = rem.get(a * n + b, 0) + c
        rem[b * n + a] = rem.get(b * n + a, 0) + c
    keys = src * np.int64(n) + dst
    rem_keys = np.fromiter(rem.keys(), np.int64, len(rem))
    hidx = np.flatnonzero(np.isin(keys, rem_keys))
    if not len(hidx):
        return src, dst
    hk = keys[hidx]
    order = np.argsort(hk, kind="stable")
    sk = hk[order]
    boundary = np.empty(len(sk), bool)
    boundary[0] = True
    np.not_equal(sk[1:], sk[:-1], out=boundary[1:])
    gid = np.cumsum(boundary) - 1
    counts = np.bincount(gid)
    starts = np.zeros(len(counts), dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    rank = np.arange(len(sk), dtype=np.int64) - starts[gid]
    remv = np.array([rem[int(x)] for x in sk[boundary]],
                    dtype=np.int64)
    keep = np.ones(len(keys), bool)
    keep[hidx[order]] = rank >= remv[gid]
    return src[keep], dst[keep]


def _survivor_arcs_from(state: PartitionState,
                        changed: np.ndarray) -> tuple:
    """Every surviving arc leaving ``changed`` (src, dst): the base
    gather minus fired tombstone occurrences, plus the symmetrized
    pending-add overlay. O(arcs touched + pending adds)."""
    sc = state._score
    src, dst = sc["index"].csr.arcs_from(changed)
    if sc["fired"] and len(src):
        src, dst = _drop_fired_arcs(src, dst, sc["fired"], state.n)
    if sc.get("ov") is None or sc.get("ov_adds") != len(state.adds):
        sc["ov"] = [np.concatenate([a, a[:, ::-1]], axis=0)
                    for a in state.adds]
        sc["ov_adds"] = len(state.adds)
    if sc["ov"]:
        mask = np.zeros(state.n, bool)
        mask[changed] = True
        parts_s, parts_d = [src], [dst]
        for arcs in sc["ov"]:
            m = mask[arcs[:, 0]]
            if m.any():
                parts_s.append(arcs[m, 0])
                parts_d.append(arcs[m, 1])
        src = np.concatenate(parts_s)
        dst = np.concatenate(parts_d)
    return src, dst


def _rescore_incremental(state: PartitionState, assigns: dict,
                         w, backend=None) -> dict:
    """The O(Δ)-per-epoch scored refresh: accumulators already carry
    the multiset delta (apply_update folded it under the cached
    assignments), so only the REASSIGNMENT delta remains — rescore the
    arcs incident to vertices whose label moved, per k. Returns the
    same ``{k: (cut, total, balance, cv)}`` shape as score_stream;
    balance is recomputed O(V) with the identical part_balance call,
    so every field is bit-equal to the full pass.

    A backend exposing ``_move_rescore`` (the multi-device backends,
    ISSUE 19) takes the rescore device-side: per-shard per-k cut
    deltas all-reduced ONCE per epoch, bit-equal to the host scorer
    (:func:`sheep_tpu.ops.score.move_rescore_sharded`)."""
    from sheep_tpu.core import pure
    from sheep_tpu.ops.refine import move_rescore_host

    sc = state._score
    prev, cut = sc["prev"], sc["cut"]
    masks = {k: prev[k] != a for k, a in assigns.items()}
    union = np.zeros(state.n, bool)
    for m in masks.values():
        union |= m
    changed = np.flatnonzero(union)
    if len(changed):
        src, dst = _survivor_arcs_from(state, changed)
        hook = getattr(backend, "_move_rescore", None)
        ks_m = [k for k in assigns if masks[k].any()]
        if hook is not None and ks_m:
            deltas = hook(src, dst,
                          {k: prev[k] for k in ks_m},
                          {k: assigns[k] for k in ks_m},
                          {k: masks[k] for k in ks_m})
            for k in ks_m:
                cut[k] += deltas[k]
            state.stats["score_distributed"] = \
                state.stats.get("score_distributed", 0) + 1
        else:
            for k in ks_m:
                cut[k] += move_rescore_host(src, dst, prev[k],
                                            assigns[k], masks[k])
    out = {}
    for k, a in assigns.items():
        prev[k] = np.array(a, copy=True)
        out[k] = (int(cut[k]), int(sc["total"]),
                  pure.part_balance(a, k, w), None)
    return out


def apply_update(backend, state: PartitionState, adds=None,
                 deletes=None, epoch: Optional[int] = None,
                 score: bool = True, compact: str = "auto",
                 comm_volume: bool = False):
    """Apply one delta epoch (module docstring). Returns the refreshed
    :class:`~sheep_tpu.types.PartitionResult` (list when the state
    carries several ks) when ``score``, else None. An ``epoch`` at or
    below the state's is an idempotent no-op returning None —
    the served retry/replay contract."""
    if compact not in ("auto", "never", "force"):
        raise ValueError(f"bad compact mode {compact!r}")
    if epoch is not None and int(epoch) <= state.epoch:
        return None  # already applied — idempotent replay
    t0 = time.perf_counter()
    n = state.n
    adds = _validate_delta(adds if adds is not None else [], n, "adds")
    dels = _validate_delta(deletes if deletes is not None else [], n,
                           "deletes")
    sp = obs.begin("partition_update", epoch=int(epoch or
                                                state.epoch + 1),
                   adds=len(adds), dels=len(dels))
    try:
        if len(adds):
            backend._fold_delta(state, adds)
            state.adds.append(adds)
            state.total_edges += len(adds)
            _account_adds(state, adds)
        if len(dels):
            from sheep_tpu.io.deltalog import cancel_adds

            # resolve NOW, against the multiset as it stands: cancel
            # pending adds first (they leave the survivor stream; the
            # folded tree keeps them until compaction — the stale-tree
            # semantics), the remainder tombstone base occurrences.
            # Matching net_effect's in-order rule keeps the one-shot
            # replay and this path describing the same multiset.
            state.adds, base_tombs = cancel_adds(state.adds, dels)
            if len(base_tombs):
                state.tombs.append(base_tombs)
            state.pending_tombs.append(dels)
            state.stale_deletes += len(dels)
            state.total_edges = max(0, state.total_edges - len(dels))
            _account_dels(state, dels, base_tombs)
        state.epoch = int(epoch) if epoch is not None \
            else state.epoch + 1
        state.stats["updates"] = state.stats.get("updates", 0) + 1
        state.stats["delta_adds"] = \
            state.stats.get("delta_adds", 0) + len(adds)
        state.stats["delta_deletes"] = \
            state.stats.get("delta_deletes", 0) + len(dels)
        forced = compact == "force" or (
            compact == "auto"
            and state.stale_deletes > state.resolved_compact_threshold())
        if forced:
            compact_state(backend, state, mode="auto"
                          if compact == "auto" else "full")
        obs.event("delta_epoch_applied", epoch=state.epoch,
                  adds=len(adds), dels=len(dels),
                  stale_deletes=state.stale_deletes,
                  compacted=bool(forced))
    finally:
        sp.end()
    state.stats["update_fold_s"] = round(
        state.stats.get("update_fold_s", 0.0)
        + (time.perf_counter() - t0), 6)
    if not score:
        return None
    return refresh(backend, state, comm_volume=comm_volume)


def refresh(backend, state: PartitionState, comm_volume: bool = False):
    """Materialize the resident table into scored results: tree split
    per k (O(V)), then EITHER the O(Δ) incremental rescore (cache
    seeded, no comm_volume) or one full scoring pass over the
    surviving multiset (which seeds/re-seeds the cache). Both produce
    bit-equal results; ``SHEEP_SCORE_AUDIT=1`` runs the full pass
    alongside the incremental one and raises on any divergence.
    Returns one PartitionResult, or a list for multi-k states."""
    from sheep_tpu.backends.base import score_stream
    from sheep_tpu.ops.split import tree_split_host
    from sheep_tpu.types import PartitionResult

    t0 = time.perf_counter()
    n = state.n
    parent = _parent_from_minp(state.minp, state.order, n)
    w = state.deg_anchor.astype(np.float64) \
        if state.weights == "degree" else None
    assigns = {k: tree_split_host(parent, state.pos, k, weights=w,
                                  alpha=state.alpha)
               for k in state.ks}
    split_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    sc = state._score
    if sc is not None and "prev" in sc and not comm_volume:
        scored = _rescore_incremental(state, assigns, w,
                                      backend=backend)
        state.stats["score_incremental"] = \
            state.stats.get("score_incremental", 0) + 1
        if os.environ.get("SHEEP_SCORE_AUDIT", "") not in ("", "0"):
            full = score_stream(state.survivor_stream(), assigns,
                                chunk_edges=state.chunk_edges,
                                comm_volume=False, weights=w)
            for k in state.ks:
                if tuple(scored[k]) != tuple(full[k]):
                    raise RuntimeError(
                        f"SHEEP_SCORE_AUDIT: incremental score "
                        f"diverged at epoch {state.epoch} k={k}: "
                        f"incremental={scored[k]} full={full[k]}")
    else:
        scored = score_stream(state.survivor_stream(), assigns,
                              chunk_edges=state.chunk_edges,
                              comm_volume=comm_volume, weights=w)
        state.stats["score_full"] = \
            state.stats.get("score_full", 0) + 1
        _seed_score_cache(state, assigns, scored)
    score_s = time.perf_counter() - t0
    state.stats["update_score_s"] = round(
        state.stats.get("update_score_s", 0.0) + score_s, 6)
    diag = {"epoch": float(state.epoch),
            "stale_deletes": float(state.stale_deletes),
            "compactions": float(state.compactions),
            **{k: float(v) for k, v in state.stats.items()
               if isinstance(v, (int, float))}}
    out = []
    for k in state.ks:
        cut, total, balance, cv = scored[k]
        out.append(PartitionResult(
            assignment=assigns[k], k=k, edge_cut=cut,
            total_edges=total, cut_ratio=cut / max(total, 1),
            balance=balance, comm_volume=cv,
            phase_times={"split": split_s / len(state.ks),
                         "score": score_s / len(state.ks)},
            backend=state.backend_name, diagnostics=dict(diag)))
    # the scored pass KNOWS the exact surviving count (unmatched
    # tombstones removed nothing); adopt it so the staleness fraction
    # and future compact thresholds price the real multiset
    state.total_edges = int(out[0].total_edges)
    return out[0] if len(out) == 1 else out


def compact_state(backend, state: PartitionState,
                  mode: str = "auto") -> str:
    """Compaction (module docstring). ``full`` re-anchors on fresh
    survivor degrees and refolds everything — bit-identical to a
    clean rebuild of the survivors. ``subtree`` keeps the anchored
    order and refolds only the edges touching tree-split parts the
    pending tombstones dirtied — the score-bounded local repair.
    ``auto`` picks subtree while the dirty set stays small (<= 1/4 of
    the parts), else full. Returns the mode that ran."""
    if mode not in ("auto", "full", "subtree"):
        raise ValueError(f"bad compact mode {mode!r}")
    pending = state.tomb_array(pending_only=True)
    if mode == "auto":
        mode = "full"
        if len(pending):
            k0 = state.ks[0]
            parts, _ = _dirty_parts(state, pending, k0)
            if len(parts) <= max(1, k0 // 4):
                mode = "subtree"
        elif state.epoch == state.anchored_at_epoch:
            # nothing changed since the anchor: compaction is a no-op
            state.pending_tombs = []
            state.stale_deletes = 0
            return "noop"
    sp = obs.begin("compact", mode=mode,
                   pending_deletes=int(len(pending)))
    try:
        if mode == "full":
            _compact_full(backend, state)
        else:
            _compact_subtree(backend, state, pending)
    finally:
        sp.end()
    state.pending_tombs = []
    state.stale_deletes = 0
    state.compactions += 1
    state.stats["compactions"] = state.compactions
    obs.event("compacted", mode=mode, epoch=state.epoch,
              compactions=state.compactions)
    return mode


def _dirty_parts(state: PartitionState, pending: np.ndarray,
                 k: int) -> tuple:
    """(dirty part-id set, full assignment) — the tree_split locality
    map: a part is dirty when a pending tombstone endpoint lives in
    its subtree."""
    from sheep_tpu.ops.split import tree_split_host

    parent = _parent_from_minp(state.minp, state.order, state.n)
    w = state.deg_anchor.astype(np.float64) \
        if state.weights == "degree" else None
    assign = tree_split_host(parent, state.pos, k, weights=w,
                             alpha=state.alpha)
    return set(np.unique(assign[pending.reshape(-1)]).tolist()), assign


def _compact_full(backend, state: PartitionState) -> None:
    """Clean rebuild of the surviving multiset with RE-ANCHORED order
    — literally the backend's one-shot partition over the survivor
    stream, so post-compact == from-scratch by construction."""
    res = backend.partition(state.survivor_stream(), state.ks[0],
                            weights=state.weights, comm_volume=False,
                            keep_tree=True)
    tree = res.tree
    n = state.n
    state.pos = np.asarray(tree["pos"], np.int64)[:n]
    state._order = None
    state.deg_anchor = np.asarray(tree["deg"], np.int64)[:n].copy()
    state.minp = _minp_from_parent(
        np.asarray(tree["parent"], np.int64), state.pos, n)
    state.total_edges = int(res.total_edges)
    state.anchored_at_epoch = state.epoch
    state.stats["compact_full"] = state.stats.get("compact_full", 0) + 1


def _compact_subtree(backend, state: PartitionState,
                     pending: np.ndarray) -> None:
    """tree_split-locality repair under the ANCHORED order: drop the
    carried constraints of the dirty parts (and of clean vertices
    whose parent is dirty), then refold every surviving edge with an
    endpoint in a dirty part. One read pass over the survivors, device
    folds proportional to the dirty region — the affected subtrees
    rebuild, the clean ones keep their table entries. Explicitly
    score-bounded (a clean-part fill routed through a deleted edge can
    linger until a full compaction re-anchors); the bound is pinned in
    tests/test_incremental.py."""
    n = state.n
    k0 = state.ks[0]
    dirty, assign = _dirty_parts(state, pending, k0)
    dirty_mask = np.isin(assign, np.asarray(sorted(dirty),
                                            dtype=assign.dtype))
    order = state.order
    minp = state.minp.copy()
    # a vertex is pruned when IT is dirty or its recorded parent is:
    # the kept table must only carry constraints entirely inside the
    # clean region
    parent = _parent_from_minp(minp, order, n)
    has = parent >= 0
    parent_dirty = np.zeros(n, dtype=bool)
    parent_dirty[has] = dirty_mask[parent[has]]
    prune = dirty_mask | parent_dirty
    minp[:n][prune] = n
    state.minp = minp
    cs = state.chunk_edges
    refolded = 0
    batch: list = []
    batch_n = 0

    def _flush():
        # ONE fold per accumulated batch: each _fold_delta call pays
        # an O(V) pos upload + table pull on the tpu hook, so folding
        # per survivor chunk would turn a local repair into hundreds
        # of O(V) round trips; batching keeps the device cost
        # proportional to the dirty region as promised
        nonlocal refolded, batch, batch_n
        if batch:
            backend._fold_delta(state, np.concatenate(batch))
            refolded += batch_n
            batch, batch_n = [], 0

    for chunk in state.survivor_stream().chunks(cs):
        e = np.asarray(chunk, np.int64).reshape(-1, 2)
        if not len(e):
            continue
        touch = dirty_mask[e[:, 0]] | dirty_mask[e[:, 1]]
        sub = e[touch]
        if len(sub):
            batch.append(sub)
            batch_n += len(sub)
            if batch_n >= 4 * cs:  # bound host accumulation
                _flush()
    _flush()
    state.stats["compact_subtree"] = \
        state.stats.get("compact_subtree", 0) + 1
    state.stats["compact_refolded_edges"] = \
        state.stats.get("compact_refolded_edges", 0) + refolded


def rebase_state(backend, state: PartitionState,
                 base_out: str) -> str:
    """Full compaction + BASE REWRITE (ISSUE 17): re-anchor on the
    survivors, then materialize the surviving multiset into a fresh
    mmap CSR base artifact at ``base_out`` and drop the add/tombstone
    history — the tombstone filter and anchored history become
    O(recent) instead of O(lifetime). The artifact write is atomic
    (``write_csr`` lands tmp + rename); the CALLER owns the durability
    ordering around it — snapshot referencing the new base, fsync'd
    journal record, only then old-artifact cleanup — so kill -9 at any
    point leaves either the old snapshot + old base or the new pair,
    both resumable (tools/obs_smoke.sh leg 13 pins this). The score
    cache is dropped: the next scored refresh re-seeds over the new
    base with one full pass. Returns ``base_out``."""
    from sheep_tpu.io import csr as csr_mod
    from sheep_tpu.io.edgestream import EdgeStream

    pending = state.tomb_array(pending_only=True)
    sp = obs.begin("compact", mode="rebase",
                   pending_deletes=int(len(pending)))
    try:
        _compact_full(backend, state)
        csr_mod.write_csr(base_out, state.survivor_stream(),
                          n_vertices=state.n,
                          chunk_edges=state.chunk_edges)
        state.base = EdgeStream.open(base_out)
        state.base_spec = base_out
        state.adds = []
        state.tombs = []
        state.pending_tombs = []
        state.stale_deletes = 0
        _drop_score_cache(state)
    finally:
        sp.end()
    state.compactions += 1
    state.stats["compactions"] = state.compactions
    state.stats["rebase"] = state.stats.get("rebase", 0) + 1
    obs.event("compacted", mode="rebase", epoch=state.epoch,
              compactions=state.compactions, base=base_out)
    return base_out


# ----------------------------------------------------------------------
# durability: resident-state snapshots (the served layer checkpoints a
# resident partition after every applied epoch; ISSUE 15 (c))
# ----------------------------------------------------------------------
STATE_VERSION = 1


def save_state(state: PartitionState, path: str) -> None:
    """Atomic snapshot (tmp + rename + fsync): arrays + meta. The base
    stream itself is NOT serialized — ``load_state`` re-opens it from
    ``base_spec`` (or takes an open stream)."""
    meta = {"v": STATE_VERSION, "n": state.n, "ks": state.ks,
            "weights": state.weights, "alpha": state.alpha,
            "chunk_edges": state.chunk_edges,
            "backend_name": state.backend_name,
            "base_spec": state.base_spec, "epoch": state.epoch,
            "anchored_at_epoch": state.anchored_at_epoch,
            "stale_deletes": state.stale_deletes,
            "compactions": state.compactions,
            "compact_threshold": state.compact_threshold,
            "total_edges": state.total_edges}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, meta=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8),
            pos=state.pos, deg_anchor=state.deg_anchor,
            minp=state.minp,
            adds=state.adds_array(), tombs=state.tomb_array(),
            pending_tombs=state.tomb_array(pending_only=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_state(path: str, base=None) -> PartitionState:
    """Reload a snapshot; ``base`` overrides re-opening ``base_spec``
    (in-memory bases cannot be re-opened from a spec)."""
    from sheep_tpu.io.edgestream import open_input

    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode("utf-8"))
        if int(meta.get("v", 0)) > STATE_VERSION:
            raise ValueError(f"{path}: resident state v{meta.get('v')} "
                             f"is newer than this reader")
        arrays = {k: z[k] for k in ("pos", "deg_anchor", "minp",
                                    "adds", "tombs",
                                    "pending_tombs")}
    if base is None:
        if not meta.get("base_spec"):
            raise ValueError(f"{path}: state has no base_spec; pass "
                             f"the base stream explicitly")
        base = open_input(meta["base_spec"])
    st = PartitionState(
        n=int(meta["n"]), ks=[int(k) for k in meta["ks"]],
        weights=meta["weights"], alpha=float(meta["alpha"]),
        chunk_edges=int(meta["chunk_edges"]),
        backend_name=meta["backend_name"],
        pos=arrays["pos"].astype(np.int64),
        deg_anchor=arrays["deg_anchor"].astype(np.int64),
        minp=arrays["minp"].astype(np.int32),
        total_edges=int(meta["total_edges"]), base=base,
        base_spec=meta.get("base_spec"), epoch=int(meta["epoch"]),
        anchored_at_epoch=int(meta.get("anchored_at_epoch", 0)),
        stale_deletes=int(meta["stale_deletes"]),
        compactions=int(meta["compactions"]),
        compact_threshold=meta.get("compact_threshold"))
    if len(arrays["adds"]):
        st.adds = [arrays["adds"].astype(np.int64).reshape(-1, 2)]
    if len(arrays["tombs"]):
        st.tombs = [arrays["tombs"].astype(np.int64).reshape(-1, 2)]
    if len(arrays["pending_tombs"]):
        st.pending_tombs = [arrays["pending_tombs"]
                            .astype(np.int64).reshape(-1, 2)]
    return st
