"""CLI driver (SURVEY.md §2 #12, §3.1).

The reference's entry point, rebuilt:

    python -m sheep_tpu.cli --input g.edges --k 64 --backend tpu \
        --output parts.bin

Prints per-phase timing and scores (edge cut, cut ratio, balance, comm
volume) as human-readable lines plus one machine-readable JSON line, and
writes the vertex->part map. ``--backend`` selects the execution strategy
via the Partitioner plugin registry [NORTH-STAR].
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sheep",
        description="TPU-native distributed graph partitioner "
                    "(SHEEP elimination-tree algorithm)",
        epilog="server mode: `sheep serve --socket PATH` runs the "
               "resident sheepd daemon (warm compiled programs, "
               "multi-tenant job queue); `sheep submit --server PATH "
               "--input G --k N` submits to one (--watch for live "
               "progress); `sheep top --server PATH` is the live "
               "telemetry console. See README 'Server mode' and "
               "'Live telemetry'.",
    )
    p.add_argument("--input",
                   help="edge list (.edges/.txt text, .bin32/.bin64 "
                        "binary), or a synthetic stream spec: "
                        "rmat-hash:SCALE[:EF[:SEED]] (device-generated "
                        "chunks on TPU backends) or rmat:SCALE[:EF[:SEED]]")
    p.add_argument("--k", help="number of parts; a comma list (e.g. "
                               "--k 8,64,256) splits ONE elimination-tree "
                               "build for every k (the tree is "
                               "k-independent), one result line each")
    p.add_argument("--backend", default=None,
                   help="execution backend (default: best available; see --list-backends)")
    p.add_argument("--k-levels", default=None, metavar="K1,K2",
                   help="hierarchical partitioning into K1*K2*... parts: "
                        "partition + refine at K1, recurse into each "
                        "part's induced subgraph for the remaining "
                        "levels. --refine rounds apply at EVERY level "
                        "(default 8 when --refine is 0). Recovers "
                        "community structure where flat k stalls below "
                        "the LP signal threshold (BASELINE.md 'SBM "
                        "quality'); replaces --k. Combines with "
                        "--checkpoint-dir/--resume (chunk-level inside "
                        "level 0, level-boundary for the recursion) and "
                        "with multi-host flags (level 0 is an ordinary "
                        "flat partition)")
    p.add_argument("--final-refine", type=int, default=None, metavar="N",
                   help="with --k-levels (or --auto-recipe): N "
                        "warm-start LP rounds at the FULL k after "
                        "hierarchical assembly (level-1 leakage repair; "
                        "the LP signal objection applies to cold starts "
                        "only)")
    p.add_argument("--auto-recipe", action="store_true",
                   help="let the quality advisor pick the hierarchy "
                        "recipe when the intra-degree/k signal says flat "
                        "label propagation will stall at --k (below the "
                        "measured threshold a naive --k 64 --refine 30 "
                        "silently lands ~0.85 cut on community graphs "
                        "where the recipe lands ~0.13). Without this "
                        "flag the advisor only prints its "
                        "recommendation; with it, the run becomes the "
                        "exact --k-levels/--final-refine/--balance "
                        "invocation it prints — bit-identical to "
                        "passing those flags by hand")
    p.add_argument("--spill-dir", default=None, metavar="DIR",
                   help="with --k-levels: where per-part intra-edge "
                        "shards spill (default: system temp). Disk "
                        "high-water mark is 8 bytes per intra edge of "
                        "the current level")
    p.add_argument("--deltas", default=None, metavar="LOG",
                   help="incremental replay (ISSUE 15): build --input, "
                        "then fold the delta log's epochs "
                        "(io/deltalog.py add/tombstone batches) into "
                        "the converged table in O(Δ) each — "
                        "bit-identical to a one-shot build of the "
                        "delta: input at the final epoch; deletions "
                        "tombstone and compact (see README "
                        "'Incremental updates'). Single k, flat path, "
                        "single-device backends (tpu/cpu/pure)")
    p.add_argument("--score-only", default=None, metavar="PARTS",
                   help="skip partitioning: score this existing partition "
                        "map (.parts/.pbin) against --input — the "
                        "standalone edge_cut_score() use case; --k is "
                        "inferred from the map if omitted")
    p.add_argument("--output", default=None,
                   help="partition map output (.parts text or .pbin binary)")
    p.add_argument("--weights", choices=["unit", "degree"], default="unit",
                   help="vertex weights for balance (default unit)")
    p.add_argument("--alpha", type=float, default=1.0,
                   help="bag capacity factor for the tree split (default "
                        "1.0; delivered balance is bounded by 1 + alpha "
                        "+ k*max_weight/total — see --balance for the "
                        "contract form)")
    p.add_argument("--balance", type=float, default=None, metavar="BETA",
                   help="guaranteed balance bound: deliver max part load "
                        "<= BETA * (total/k) + max vertex weight (+ one "
                        "weight unit on tiny parts, total/k < "
                        "1/(BETA-1), where the bag capacity floors at a "
                        "single unit), by running the split at alpha = "
                        "BETA - 1 (measured cut cost ~1-2.5%% at BETA "
                        "1.1-1.3, BASELINE.md balance table); BETA > 1, "
                        "mutually exclusive with --alpha")
    p.add_argument("--segment-rounds", type=int, default=None,
                   help="fixpoint rounds per device execution (tpu "
                        "backend; default 2 — tuned on the v5e)")
    p.add_argument("--warm-schedule", default=None, metavar="R:L[,R:L...]",
                   help="low-lift warm rounds before full-depth rounds, "
                        "e.g. '1:8' (the tpu backend's tuned default) or "
                        "'' to disable")
    p.add_argument("--host-tail-threshold", type=int, default=None,
                   help="hand the fixpoint tail to the native host core "
                        "at this live-constraint count (tpu backend; "
                        "default: chunk/2 on accelerators, auto on cpu)")
    p.add_argument("--no-cache-chunks", action="store_true",
                   help="disable the device-resident edge-chunk cache "
                        "(tpu backend re-streams each pass)")
    p.add_argument("--carry-tail", dest="carry_tail", action="store_true",
                   default=None,
                   help="carry intermediate chunks' fixpoint tails into "
                        "the next chunk's fold instead of host-finishing "
                        "each one (tpu backend; default off — measured "
                        "slower except on extreme-latency device links, "
                        "see BASELINE.md)")
    p.add_argument("--no-carry-tail", dest="carry_tail",
                   action="store_false",
                   help="host-finish every chunk's tail (see --carry-tail)")
    p.add_argument("--tail-overlap", dest="tail_overlap",
                   action="store_true", default=None,
                   help="resolve each chunk's fixpoint tail on host in a "
                        "worker thread while the device folds the next "
                        "chunk; resolved links re-enter a later fold as "
                        "O(changed) delta constraints (tpu backend; same "
                        "forest bit-for-bit; excludes --carry-tail)")
    p.add_argument("--no-tail-overlap", dest="tail_overlap",
                   action="store_false",
                   help="serialize host tails (see --tail-overlap)")
    p.add_argument("--stale-reuse", type=int, default=None,
                   help="tpu backend: full segments per lifting-stack "
                        "rebuild (1 = per-segment hoisting; K > 1 reuses "
                        "one stale stack across K segments)")
    p.add_argument("--dispatch-batch", type=int, default=None, metavar="N",
                   help="tpu/tpu-sharded: stage N streamed chunks as one "
                        "padded [N, C] block and fold them in single "
                        "bounded device programs — one packed stats sync "
                        "per execution instead of per fixpoint segment "
                        "(0 = auto: per-segment on cpu-jax, HBM-model-"
                        "sized N on accelerators; 1 = per-segment "
                        "dispatch; the forest is bit-identical either "
                        "way). Excludes --carry-tail/--tail-overlap")
    p.add_argument("--inflight", type=int, default=None, metavar="D",
                   help="tpu/tpu-sharded: depth of the asynchronous "
                        "dispatch pipeline — keep up to D batched device "
                        "executions in flight with their packed stats "
                        "words read one-behind, so host staging, H2D "
                        "transfer and the device fixpoint overlap "
                        "instead of alternating (0 = auto: 2 on "
                        "accelerators, 1 on cpu-jax; 1 = synchronous "
                        "dispatch; the forest is bit-identical at every "
                        "depth). Excludes --carry-tail/--tail-overlap")
    p.add_argument("--h2d-ring", type=int, default=None, metavar="D",
                   help="tpu backend: staged host->device ring depth — "
                        "keep up to D pre-padded chunk blocks' "
                        "device_put transfers issued ahead of the "
                        "dispatch chain, so the upload of block i+D "
                        "overlaps the fold of block i (0 = auto: 2 on "
                        "accelerators, 1 on cpu-jax; bit-identical at "
                        "every depth). Device-generated synthetic "
                        "streams (rmat-hash:/sbm-hash:) synthesize "
                        "chunks in accelerator memory and skip staging "
                        "entirely")
    p.add_argument("--lift-levels", type=int, default=None,
                   help="binary-lifting depth of the fixpoint climb "
                        "(0 = auto; tpu and tpu-bigv backends)")
    p.add_argument("--jumps", type=int, default=None,
                   help="tpu-bigv: single-step climbs per tail round")
    p.add_argument("--hoist-bytes", type=int, default=None,
                   help="tpu-bigv: per-device HBM budget for the "
                        "per-segment stale lifting stack (0 = per-round "
                        "squaring, the measured default; see BASELINE.md)")
    p.add_argument("--chunk-edges", type=int, default=None,
                   help="edges per streamed chunk (default backend-specific)")
    p.add_argument("--refine", type=int, default=None, metavar="N",
                   help="post-pass: up to N rounds of capacity-constrained "
                        "label propagation (cut never regresses; extension "
                        "beyond the reference). Default 0 for flat runs; "
                        "--k-levels defaults to 8 per level (pass an "
                        "explicit 0 for unrefined levels)")
    p.add_argument("--refine-alpha", type=float, default=1.10,
                   help="refinement balance cap (x ceil(V/k) per part)")
    p.add_argument("--refine-budget-gb", type=float, default=4.0,
                   metavar="GB",
                   help="histogram budget for refinement: above "
                        "(V+1)*k*4 bytes it switches to multi-pass "
                        "blocked mode (bit-identical, ~(2B+1)/2x the "
                        "stream passes at B blocks). s22/k=256 misses "
                        "the 4 GB default by 1 KB — raise on big-RAM "
                        "hosts")
    p.add_argument("--no-comm-volume", action="store_true",
                   help="skip communication-volume computation (saves a pass of memory)")
    p.add_argument("--num-vertices", type=int, default=None,
                   help="vertex count if known (skips a counting pass)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax profiler trace (tpu backend) to this dir")
    p.add_argument("--metrics-out", default=None,
                   help="append structured JSONL metrics (phases, scores, "
                        "part loads, device memory) to this file")
    p.add_argument("--trace", default=None, metavar="FILE",
                   help="append a structured trace (JSONL: run manifest, "
                        "hierarchical span tree with counter deltas, "
                        "heartbeats, scores) to FILE; render with "
                        "tools/trace_report.py. Multi-host runs trace on "
                        "process 0 only")
    p.add_argument("--heartbeat-secs", type=float, default=None,
                   metavar="S",
                   help="with --trace: emit a progress heartbeat record "
                        "(phase, chunks done, edges/sec, ETA, dispatch "
                        "counts, device memory) every S seconds, plus one "
                        "final flush — a dead run stops heartbeating, a "
                        "slow one doesn't")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save O(V) chunk-level checkpoints to this dir")
    p.add_argument("--checkpoint-every", type=int, default=64,
                   help="checkpoint cadence in chunks (default 64)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --checkpoint-dir")
    p.add_argument("--json", action="store_true", help="print only the JSON result line")
    p.add_argument("--list-backends", action="store_true", help="list backends and exit")
    from sheep_tpu import __version__

    p.add_argument("--version", action="version",
                   version=f"sheep_tpu {__version__}")
    mh = p.add_argument_group("multi-host (the reference's mpirun equivalent)")
    mh.add_argument("--coordinator", default=None,
                    help="coordinator address host:port; launch one process "
                         "per host with the same value")
    mh.add_argument("--num-processes", type=int, default=None,
                    help="total number of processes in the multi-host run")
    mh.add_argument("--process-id", type=int, default=None,
                    help="this process's rank in [0, num_processes)")
    return p


def _parse_warm_schedule(spec: str, parser) -> tuple:
    """'R:L[,R:L...]' -> ((R, L), ...); '' -> (); malformed input is an
    argparse error at parse time, not a mid-partition crash."""
    out = []
    for part in spec.split(","):
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 2 or not all(b.isdigit() for b in bits):
            parser.error(f"--warm-schedule: expected R:L pairs, got {part!r}")
        rounds, levels = int(bits[0]), int(bits[1])
        if rounds < 1 or levels < 1:
            parser.error(f"--warm-schedule: R and L must be >= 1 in {part!r}")
        out.append((rounds, levels))
    return tuple(out)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # server verbs (ISSUE 10): `sheep serve ...` runs the resident
    # daemon, `sheep submit ...` talks to one — both also installed as
    # standalone console scripts (sheepd / sheep-submit). Dispatched
    # before argparse so the flat flag grammar stays untouched.
    if argv and argv[0] == "serve":
        from sheep_tpu.server.daemon import main as daemon_main

        return daemon_main(argv[1:])
    if argv and argv[0] == "submit":
        from sheep_tpu.server.client import main as submit_main

        return submit_main(argv[1:])
    if argv and argv[0] == "update":
        # ISSUE 15: `sheep update JOB --server S --deltas LOG` streams
        # a delta log's epochs at a resident served partition (sugar
        # over sheep-submit --update)
        from sheep_tpu.server.client import main as submit_main

        rest = list(argv[1:])
        if rest and not rest[0].startswith("-"):
            rest = ["--update", rest[0]] + rest[1:]
        return submit_main(rest)
    if argv and argv[0] == "top":
        # ISSUE 11: the live telemetry console (also installed as the
        # standalone `sheeptop` console script)
        from sheep_tpu.server.sheeptop import main as top_main

        return top_main(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.heartbeat_secs is not None:
        if args.trace is None:
            parser.error("--heartbeat-secs requires --trace (heartbeats "
                         "are trace records)")
        if args.heartbeat_secs <= 0:
            parser.error("--heartbeat-secs must be > 0")
    # multi-host: one trace file, written by process 0 (every other rank
    # runs untraced — the obs facade is a no-op without an installed
    # tracer, so the instrumented loops cost nothing there). A
    # rank-autodetected launch (--coordinator without --process-id)
    # cannot know its rank this early, so it runs untraced rather than
    # risking every rank appending to one file.
    multi_host = args.coordinator or args.num_processes
    is_rank0 = args.process_id == 0 or (args.process_id is None
                                        and not multi_host)
    if args.trace is None or not is_rank0:
        return _run(parser, args)

    # pin the platform BEFORE the manifest's topology probe, for the
    # same reason _run pins it before touching backends (a TPU plugin
    # pre-import makes JAX_PLATFORMS a no-op on its own)
    from sheep_tpu.utils.platform import pin_platform

    pin_platform()
    from sheep_tpu import obs

    tracer = obs.install(obs.Tracer(args.trace))
    root = None
    try:
        if not multi_host:
            _start_trace_run(tracer, args)
        # multi-host: the manifest's topology probe would initialize the
        # jax backend, and jax.distributed.initialize REQUIRES that no
        # computation ran yet — _run emits manifest + starts the
        # heartbeat right after the distributed bring-up instead
        root = obs.begin("run")
        return _run(parser, args)
    finally:
        if tracer.heartbeat is not None:
            tracer.heartbeat.stop()
        if root is not None:
            root.end()
        obs.uninstall()
        tracer.close()


def _start_trace_run(tracer, args) -> None:
    """Manifest + heartbeat for a traced run. Called only once probing
    the jax topology is safe: immediately for single-process runs,
    after ``jax.distributed.initialize`` for multi-host ones."""
    from sheep_tpu import obs

    obs.emit_manifest(tracer, config=vars(args), backend=args.backend)
    if args.heartbeat_secs:
        tracer.heartbeat = obs.Heartbeat(
            tracer, args.heartbeat_secs).start()


def _multihost_setup(args) -> tuple:
    """Distributed bring-up shared by the flat and --k-levels paths:
    initialize the runtime, resolve rank, default the backend to the
    sharded one, then start the deferred trace (the manifest's topology
    probe is only safe after jax.distributed.initialize, and it sits
    after the backend default so the manifest records the backend that
    will actually run). Returns (is_main, process_id, nprocs)."""
    from sheep_tpu.parallel.mesh import init_distributed

    init_distributed(args.coordinator, args.num_processes, args.process_id)
    import jax

    process_id = jax.process_index()
    nprocs = jax.process_count()
    if args.backend is None:
        args.backend = "tpu-sharded"
    from sheep_tpu import obs

    tracer = obs.get_tracer()
    if tracer is not None:
        _start_trace_run(tracer, args)
    return process_id == 0, process_id, nprocs


def _run(parser, args) -> int:

    def _score_only(args):
        """--score-only PARTS: evaluate an existing partition map against
        the input — the reference's standalone edge_cut_score() path."""
        import numpy as np

        from sheep_tpu.backends.base import score_stream
        from sheep_tpu.io.edgestream import open_input
        from sheep_tpu.io.formats import read_partition

        assignment = read_partition(args.score_only)
        with open_input(args.input, n_vertices=args.num_vertices) as es:
            n = es.num_vertices
            if len(assignment) != n:
                print(f"error: partition map has {len(assignment)} "
                      f"entries, graph has {n} vertices", file=sys.stderr)
                return 2
            k = int(args.k) if args.k is not None \
                else int(assignment.max()) + 1
            if assignment.min() < 0 or assignment.max() >= k:
                print(f"error: partition map assigns parts outside "
                      f"[0, {k})", file=sys.stderr)
                return 2
            t0 = time.perf_counter()
            w = None
            if args.weights == "degree":
                w = np.zeros(n, dtype=np.int64)
                for c in es.chunks(args.chunk_edges or (1 << 22)):
                    w += np.bincount(np.asarray(c, np.int64).ravel(),
                                     minlength=n)[:n]
            cut, total, balance, cv = score_stream(
                es, {k: assignment},
                chunk_edges=args.chunk_edges or (1 << 22),
                comm_volume=not args.no_comm_volume, weights=w)[k]
            wall = time.perf_counter() - t0
        line = {"k": k, "edge_cut": cut, "total_edges": total,
                "cut_ratio": cut / max(total, 1), "balance": balance,
                "comm_volume": cv, "backend": "score-only",
                "wall_seconds": round(wall, 4), "n_vertices": n}
        from sheep_tpu import obs

        obs.event("scores", **line)
        if not args.json:
            print(f"score-only: {args.score_only} vs {args.input}")
            print(f"k={k}: edge cut {cut:,} "
                  f"({100 * cut / max(total, 1):.2f}%)  "
                  f"balance {balance:.4f}"
                  + (f"  comm volume {cv:,}" if cv is not None else ""))
        print(json.dumps(line))
        return 0

    # Honor JAX_PLATFORMS even though a TPU platform plugin may pre-import
    # jax at interpreter startup (which makes the env var a no-op on its
    # own). Without this, `JAX_PLATFORMS=cpu python -m sheep_tpu.cli ...`
    # hangs trying to initialize an unreachable accelerator.
    from sheep_tpu.utils.platform import enable_compilation_cache, \
        pin_platform

    pin_platform()
    enable_compilation_cache()

    from sheep_tpu import list_backends
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io.edgestream import open_input
    from sheep_tpu.io.formats import write_partition
    from sheep_tpu.types import UnsupportedGraphError

    if args.list_backends:
        print(" ".join(list_backends()))
        return 0
    if args.input is None or (args.k is None and not args.score_only
                              and not args.k_levels):
        build_parser().error("--input and --k are required")

    def _k_levels(args):
        """--k-levels K1,K2: hierarchical partitioning via the library's
        partition_hierarchical (see sheep_tpu/hierarchy.py)."""
        import sheep_tpu

        if args.k is not None:
            parser.error("--k-levels replaces --k")
        if args.resume and not args.checkpoint_dir:
            parser.error("--resume requires --checkpoint-dir")
        if args.balance is not None and args.alpha != 1.0:
            parser.error("--balance sets the per-level alpha "
                         "(BETA**(1/levels) per level); do not also "
                         "pass --alpha")
        # every other flag either forwards below or must not silently
        # diverge from what was requested
        ignored = [f for f, v in (
            ("--metrics-out", args.metrics_out),
            ("--profile-dir", args.profile_dir),
            ("--segment-rounds", args.segment_rounds),
            ("--warm-schedule", args.warm_schedule),
            ("--host-tail-threshold", args.host_tail_threshold),
            ("--no-cache-chunks", args.no_cache_chunks or None),
            ("--carry-tail", args.carry_tail),
            ("--tail-overlap", args.tail_overlap),
            ("--stale-reuse", args.stale_reuse),
            ("--dispatch-batch", args.dispatch_batch),
            ("--inflight", args.inflight),
            ("--h2d-ring", args.h2d_ring),
            ("--lift-levels", args.lift_levels),
            ("--jumps", args.jumps),
            ("--hoist-bytes", args.hoist_bytes),
            ("--deltas", args.deltas),
        ) if v is not None]
        if ignored:
            parser.error(f"{', '.join(ignored)} not supported with "
                         f"--k-levels (would be silently ignored)")
        try:
            levels = [int(x) for x in args.k_levels.split(",") if x != ""]
        except ValueError:
            levels = []
        if not levels or any(k < 1 for k in levels):
            parser.error(f"--k-levels must be a comma list of "
                         f"positive ints (got {args.k_levels!r})")

        # multi-host: level 0 is an ordinary flat partition, so the
        # same distributed bring-up as the flat path applies; the
        # recursion then runs identically (and deterministically) on
        # every process, keeping collective schedules in lockstep
        is_main, process_id, nprocs = True, 0, 1
        if args.coordinator or args.num_processes:
            is_main, process_id, nprocs = _multihost_setup(args)

        ckpt_kw = {}
        if args.checkpoint_dir:
            from sheep_tpu.utils.checkpoint import Checkpointer

            ckpt_kw = {
                "checkpointer": Checkpointer(args.checkpoint_dir,
                                             every=args.checkpoint_every,
                                             process=process_id),
                "resume": args.resume,
                "nprocs": nprocs,
            }
        t0 = time.perf_counter()
        res = sheep_tpu.partition_hierarchical(
            args.input, levels, backend=args.backend,
            refine=8 if args.refine is None else args.refine,
            refine_alpha=args.refine_alpha,
            chunk_edges=args.chunk_edges or (1 << 22),
            comm_volume=not args.no_comm_volume, weights=args.weights,
            balance=args.balance, final_refine=args.final_refine or 0,
            spill_dir=args.spill_dir, n_vertices=args.num_vertices,
            refine_budget_bytes=int(args.refine_budget_gb * (1 << 30)),
            **ckpt_kw,
            **({} if args.balance is not None else
               {"alpha": args.alpha}))
        wall = time.perf_counter() - t0
        if not is_main:
            return 0
        if args.output:
            write_partition(args.output, res.assignment)
        summary = res.summary()
        summary["wall_seconds"] = round(wall, 4)
        summary["n_vertices"] = int(len(res.assignment))
        from sheep_tpu import obs

        obs.event("scores", **summary)
        if not args.json:
            print(f"graph: {args.input}  k-levels: {levels}")
            print(f"k={res.k}: edge cut {res.edge_cut:,} "
                  f"({100 * res.cut_ratio:.2f}%)  balance "
                  f"{res.balance:.4f}"
                  + (f"  comm volume {res.comm_volume:,}"
                     if res.comm_volume is not None else ""))
            if args.output:
                print(f"partition map written to {args.output}")
            print(f"wall: {wall:.2f}s")
        print(json.dumps(summary))
        return 0

    if args.k_levels:
        if args.score_only:
            build_parser().error("--k-levels does not combine with "
                                 "--score-only")
        if args.auto_recipe:
            build_parser().error("--auto-recipe asks the advisor to "
                                 "pick the levels; it replaces "
                                 "--k-levels")
        return _k_levels(args)
    if (args.final_refine and not args.auto_recipe) or args.spill_dir:
        build_parser().error("--final-refine/--spill-dir require "
                             "--k-levels (the flat pipeline has no "
                             "hierarchy to repair or spill; "
                             "--final-refine also composes with "
                             "--auto-recipe)")
    if args.auto_recipe and args.score_only:
        build_parser().error("--auto-recipe has no effect with "
                             "--score-only (nothing is partitioned)")
    if args.score_only:
        if args.deltas:
            build_parser().error("--deltas does not combine with "
                                 "--score-only (score the delta: "
                                 "input spec instead)")
        if args.balance is not None:
            build_parser().error("--balance has no effect with "
                                 "--score-only (the split already "
                                 "happened)")
        if args.k is not None:
            raw_k = args.k
            try:
                args.k = int(raw_k)
            except ValueError:
                args.k = 0
            if args.k < 1:
                build_parser().error(f"--score-only takes a single "
                                     f"positive --k (got {raw_k!r})")
        return _score_only(args)
    try:
        ks = [int(x) for x in str(args.k).split(",") if x != ""]
    except ValueError:
        ks = []
    if not ks or any(k < 1 for k in ks):
        build_parser().error(f"--k must be a positive int or comma list "
                             f"of them (got {args.k!r})")
    # duplicate ks would alias the per-k output paths and the marginal
    # wall accounting (both are keyed by k): dedupe preserving order
    ks = list(dict.fromkeys(ks))
    if len(ks) > 1 and (args.checkpoint_dir or args.refine):
        build_parser().error("--k lists do not combine with "
                             "--checkpoint-dir or --refine; run those "
                             "single-k")
    args.k = ks[0]
    if args.deltas:
        # the incremental replay is a flat, single-k, single-device
        # path; every combination it cannot honor is rejected up front
        bad = [f for f, v in (
            ("--k lists", len(ks) > 1 or None),
            ("--refine", args.refine),
            ("--auto-recipe", args.auto_recipe or None),
            ("--checkpoint-dir", args.checkpoint_dir),
            ("--resume", args.resume or None),
            ("--coordinator/--num-processes",
             args.coordinator or args.num_processes),
        ) if v]
        if bad:
            build_parser().error(f"{', '.join(bad)} not supported "
                                 f"with --deltas (the incremental "
                                 f"replay is flat, single-k, "
                                 f"single-process)")
        if not os.path.exists(args.deltas):
            build_parser().error(f"--deltas {args.deltas!r} does not "
                                 f"exist")
    if args.resume and not args.checkpoint_dir:
        build_parser().error("--resume requires --checkpoint-dir")
    if args.carry_tail and args.tail_overlap:
        build_parser().error("--carry-tail and --tail-overlap are mutually "
                             "exclusive tail strategies")
    if args.auto_recipe and len(ks) > 1:
        build_parser().error("--auto-recipe takes a single --k (the "
                             "recipe is per target k)")
    if args.auto_recipe:
        # flags a --k-levels run cannot honor are rejected UP FRONT:
        # letting them through would make the same command line a
        # usage error or not depending on the input's degree signal
        # (and the eventual error would name --k-levels, a flag the
        # user never passed)
        unsupported = [f for f, v in (
            ("--metrics-out", args.metrics_out),
            ("--profile-dir", args.profile_dir),
            ("--segment-rounds", args.segment_rounds),
            ("--warm-schedule", args.warm_schedule),
            ("--host-tail-threshold", args.host_tail_threshold),
            ("--no-cache-chunks", args.no_cache_chunks or None),
            ("--carry-tail", args.carry_tail),
            ("--tail-overlap", args.tail_overlap),
            ("--stale-reuse", args.stale_reuse),
            ("--dispatch-batch", args.dispatch_batch),
            ("--inflight", args.inflight),
            ("--h2d-ring", args.h2d_ring),
            ("--lift-levels", args.lift_levels),
            ("--jumps", args.jumps),
            ("--hoist-bytes", args.hoist_bytes),
        ) if v is not None]
        if unsupported:
            build_parser().error(
                f"{', '.join(unsupported)} not supported with "
                f"--auto-recipe (the applied recipe is a --k-levels "
                f"run, which does not take them)")

    # ---- quality advisor (ISSUE 13) ----------------------------------
    # The degree pass's cheapest statistic (2E/V, O(1) for binary and
    # synthetic inputs) prices the LP signal BEFORE any device work: a
    # naive flat --k below the threshold silently lands an ~0.85-class
    # cut on community graphs where the three-flag hierarchy recipe
    # lands ~0.13 — so the tool now SAYS so, and --auto-recipe makes
    # the run the exact recipe invocation it prints (bit-identical to
    # the manual flags by construction: same code path, same knobs).
    if len(ks) == 1 and not args.score_only:
        advice = None
        try:
            with open_input(args.input,
                            n_vertices=args.num_vertices) as es0:
                from sheep_tpu.ops.degrees import advise_recipe

                m = es0.num_edges_cheap
                # the signal must stay O(1): never pay a stream scan
                # just to advise. num_edges_cheap is O(1) or None by
                # contract, but num_vertices SCANS the file for
                # binary/text inputs unless the caller supplied it —
                # synthetic/memory streams (no path) and CSR headers
                # are arithmetic, and an already-known _n_vertices
                # (--num-vertices) is free.
                cheap_v = (getattr(es0, "path", None) is None
                           or getattr(es0, "fmt", None) == "csr"
                           or getattr(es0, "_n_vertices", None)
                           is not None)
                if m is not None and cheap_v:
                    advice = advise_recipe(es0.num_vertices, m, args.k)
                else:
                    advice = {"mode": "unknown", "signal": None,
                              "k": args.k}
        except (OSError, ValueError):
            pass  # unopenable input: the main path raises the real error
        # mirror the trace gating: print on rank 0, and not at all on
        # rank-autodetected launches (every rank would print)
        adv_main = args.process_id == 0 or (
            args.process_id is None
            and not (args.coordinator or args.num_processes))
        if advice is not None and advice["mode"] == "hier":
            lv = ",".join(str(x) for x in advice["k_levels"])
            # `is None` tests: an EXPLICIT --final-refine 0 /
            # --balance must survive into the applied recipe
            fr = advice["final_refine"] if args.final_refine is None \
                else args.final_refine
            bal = args.balance if args.balance is not None \
                else advice["balance"]
            flags = f"--k-levels {lv} --final-refine {fr} --balance {bal}"
            if args.refine is not None:
                flags += f" --refine {args.refine}"
            if adv_main:
                print(f"note: quality advisor: intra-degree/k signal "
                      f"{advice['signal']:.2f} < "
                      f"{advice['threshold']:.2f} at k={args.k} — flat "
                      f"label propagation stalls below the signal "
                      f"threshold (BASELINE.md 'SBM quality'); "
                      f"recommended recipe: {flags}"
                      + ("" if args.auto_recipe else
                         "  (pass --auto-recipe to apply)"),
                      file=sys.stderr)
            if args.auto_recipe:
                args.k_levels = lv
                args.k = None
                args.final_refine = fr
                args.balance = bal
                return _k_levels(args)
        elif args.auto_recipe and adv_main:
            if advice is None or advice.get("signal") is None:
                why = ("the stream's size is not O(1)-knowable (text "
                       "inputs, or binary without --num-vertices), so "
                       "the signal is unknown")
            elif advice["signal"] >= advice["threshold"]:
                why = (f"signal {advice['signal']:.2f} >= "
                       f"{advice['threshold']:.2f} (flat LP is fine)")
            else:
                why = (f"signal {advice['signal']:.2f} is low but "
                       f"k={args.k} has no usable level split (prime "
                       f"past the per-level cap)")
            print(f"note: quality advisor: {why}; running the flat "
                  f"path as asked"
                  + (" (--final-refine only applies when the advisor "
                     "selects a hierarchy; ignored)"
                     if args.final_refine else ""), file=sys.stderr)

    is_main = True
    process_id = 0
    if args.coordinator or args.num_processes:
        is_main, process_id, _ = _multihost_setup(args)

    backend = args.backend
    if backend is None:
        avail = list_backends()
        backend = next(b for b in ("tpu", "cpu", "pure") if b in avail)
        auto = True
    else:
        auto = False

    t0 = time.perf_counter()
    with open_input(args.input, n_vertices=args.num_vertices) as es:
        if auto and backend.startswith("tpu") and "tpu-bigv" in list_backends():
            # replicated vertex tables past the single-chip ceiling need
            # the vertex-sharded mode (BASELINE.md HBM budget); ask the
            # real device for its memory limit, 16 GiB (v5e) fallback
            from sheep_tpu.utils.membudget import max_vertices_for

            hbm = 16 << 30
            try:
                import jax

                stats = jax.local_devices()[0].memory_stats() or {}
                hbm = int(stats.get("bytes_limit", hbm)) or hbm
            except Exception:
                pass
            cs = args.chunk_edges or (1 << 22)
            if es.num_vertices > max_vertices_for(int(0.9 * hbm), cs):
                backend = "tpu-bigv"
                print(f"note: V={es.num_vertices:,} exceeds the "
                      f"replicated-table ceiling for this device's HBM; "
                      f"auto-selected the vertex-sharded tpu-bigv backend",
                      file=sys.stderr)

        if args.balance is not None:
            if args.balance <= 1.0:
                parser.error("--balance must be > 1 (it bounds max part "
                             "load at BETA * total/k)")
            if args.alpha != 1.0:
                parser.error("--balance sets alpha = BETA - 1; do not "
                             "also pass --alpha")
            # LPT placement puts each flushed bag (<= alpha*total/k +
            # max_w) on a part whose load is <= total/k, so alpha =
            # BETA - 1 delivers max load <= BETA*total/k + max_w
            # (tests/test_balance.py pins this bound)
            args.alpha = min(args.balance - 1.0, 1.0)
            if args.refine and args.refine_alpha > args.balance:
                # refinement caps parts at refine_alpha*ceil(V/k): a
                # looser refine cap would silently void the --balance
                # contract end-to-end (ADVICE r4), so clamp it to BETA
                print(f"note: --balance {args.balance} clamps "
                      f"--refine-alpha {args.refine_alpha} to the "
                      f"contract bound", file=sys.stderr)
                args.refine_alpha = args.balance
        ctor = {"alpha": args.alpha}
        if args.chunk_edges:
            ctor["chunk_edges"] = args.chunk_edges
        if args.segment_rounds is not None:
            ctor["segment_rounds"] = args.segment_rounds
        if args.warm_schedule is not None:
            ctor["warm_schedule"] = _parse_warm_schedule(
                args.warm_schedule, parser)
        if args.host_tail_threshold is not None:
            ctor["host_tail_threshold"] = args.host_tail_threshold
        if args.no_cache_chunks:
            ctor["cache_chunks"] = False
        if args.carry_tail is not None:
            ctor["carry_tail"] = args.carry_tail
        if args.tail_overlap is not None:
            ctor["tail_overlap"] = args.tail_overlap
        if args.stale_reuse is not None:
            if args.stale_reuse < 1:
                parser.error("--stale-reuse must be >= 1")
            ctor["stale_reuse"] = args.stale_reuse
        if args.dispatch_batch is not None:
            if args.dispatch_batch < 0:
                parser.error("--dispatch-batch must be >= 0 (0 = auto)")
            if args.dispatch_batch > 1 and (args.carry_tail or
                                            args.tail_overlap):
                parser.error("--dispatch-batch > 1 folds whole segments "
                             "on device; it excludes --carry-tail/"
                             "--tail-overlap")
            ctor["dispatch_batch"] = args.dispatch_batch
        if args.inflight is not None:
            if args.inflight < 0:
                parser.error("--inflight must be >= 0 (0 = auto)")
            if args.inflight > 1 and (args.carry_tail or
                                      args.tail_overlap):
                parser.error("--inflight > 1 pipelines whole batched "
                             "executions; it excludes --carry-tail/"
                             "--tail-overlap")
            ctor["inflight"] = args.inflight
        if args.h2d_ring is not None:
            if args.h2d_ring < 0:
                parser.error("--h2d-ring must be >= 0 (0 = auto)")
            ctor["h2d_ring"] = args.h2d_ring
        if args.lift_levels is not None:
            if args.lift_levels < 0:
                parser.error("--lift-levels must be >= 0")
            ctor["lift_levels"] = args.lift_levels
        if args.jumps is not None:
            if args.jumps < 1:
                parser.error("--jumps must be >= 1")
            ctor["jumps"] = args.jumps
        if args.hoist_bytes is not None:
            if args.hoist_bytes < 0:
                parser.error("--hoist-bytes must be >= 0")
            ctor["hoist_bytes"] = args.hoist_bytes
        # keep only the options this backend's constructor names; warn
        # about the rest instead of silently changing the run (the
        # tuning knobs vary per backend; every registered backend's ctor
        # names alpha and chunk_edges, so those survive the filter for
        # the built-ins — a third-party plugin without them gets the
        # stderr note). A plugin ctor taking **kwargs
        # accepts everything; an unknown backend name falls through to
        # get_backend's friendly available-backends error.
        import inspect

        from sheep_tpu.backends.base import _REGISTRY

        cls = _REGISTRY.get(backend)
        accepted = ctor
        if cls is not None:
            params = inspect.signature(cls.__init__).parameters
            if not any(p.kind is inspect.Parameter.VAR_KEYWORD
                       for p in params.values()):
                accepted = {k: v for k, v in ctor.items() if k in params}
                dropped = sorted(set(ctor) - set(accepted))
                if dropped and is_main:
                    print(f"note: backend {backend!r} does not take "
                          f"{', '.join(dropped)}; ignored", file=sys.stderr)
        be = get_backend(backend, **accepted)
        from sheep_tpu import obs

        # the manifest records the REQUESTED backend (null for auto);
        # this event records what auto-selection actually picked —
        # trace_report's manifest line falls back to it
        obs.event("backend_resolved", backend=backend, auto=auto)
        ckpt_kw = {}
        if args.checkpoint_dir:
            from sheep_tpu.utils.checkpoint import Checkpointer

            ckpt_kw = {
                "checkpointer": Checkpointer(args.checkpoint_dir,
                                             every=args.checkpoint_every,
                                             process=process_id),
                "resume": args.resume,
            }
        profile = None
        if args.profile_dir:
            import jax

            profile = jax.profiler.trace(args.profile_dir)
            profile.__enter__()
        try:
            try:
                if args.deltas:
                    # incremental replay (ISSUE 15): base build, then
                    # fold each logged epoch into the converged table
                    # — O(Δ) per epoch, bit-identical to the one-shot
                    # delta: build at the final epoch
                    from sheep_tpu import incremental
                    from sheep_tpu.io.deltalog import DeltaLogReader

                    if not getattr(be, "supports_incremental", False):
                        print(f"error: backend {be.name!r} does not "
                              f"support incremental updates; use "
                              f"--backend tpu/cpu/pure",
                              file=sys.stderr)
                        return 2

                    state, res = incremental.begin_incremental(
                        es, args.k, backend=be, weights=args.weights,
                        comm_volume=False)
                    applied = 0
                    for ep, d_adds, d_dels in DeltaLogReader(
                            args.deltas).epochs(
                                start_epoch=state.epoch):
                        be.partition_update(state, adds=d_adds,
                                            deletes=d_dels, epoch=ep,
                                            score=False)
                        applied += 1
                    res = incremental.refresh(
                        be, state,
                        comm_volume=not args.no_comm_volume)
                    if is_main and not args.json:
                        print(f"deltas: applied {applied} epoch(s) "
                              f"from {args.deltas} -> epoch "
                              f"{state.epoch} (stale deletes "
                              f"{state.stale_deletes}, compactions "
                              f"{state.compactions})")
                elif len(ks) > 1:
                    multi = be.partition_multi(
                        es, ks, weights=args.weights,
                        comm_volume=not args.no_comm_volume)
                    res = multi[0]
                else:
                    res = be.partition(es, args.k, weights=args.weights,
                                       comm_volume=not args.no_comm_volume,
                                       **ckpt_kw)
            except UnsupportedGraphError as exc:
                # documented envelope violations (e.g. >= 2^31 vertices on
                # an int32-table TPU backend) reject cleanly, not as a
                # mid-build stack trace
                print(f"error: {exc}", file=sys.stderr)
                return 2
            if args.refine and is_main:
                from sheep_tpu import refine_result

                res = refine_result(
                    res, es, rounds=args.refine,
                    alpha=args.refine_alpha, weights=args.weights,
                    budget_bytes=int(args.refine_budget_gb * (1 << 30)))
        finally:
            if profile is not None:
                profile.__exit__(None, None, None)
        wall = time.perf_counter() - t0
        n = es.num_vertices
        m = res.total_edges

    results = multi if len(ks) > 1 else [res]

    def _out_path(k: int) -> str:
        if len(ks) == 1:
            return args.output
        root, ext = os.path.splitext(args.output)
        return f"{root}.k{k}{ext}"

    if args.output and is_main:
        for r in results:
            write_partition(_out_path(r.k), r.assignment)

    if args.metrics_out and is_main:
        from sheep_tpu.utils.metrics import MetricsWriter, emit_run_metrics

        with MetricsWriter(args.metrics_out) as mw:
            for r in results:
                emit_run_metrics(mw, r, n, wall, graph=args.input)

    from sheep_tpu import obs

    tracer = obs.get_tracer()
    if tracer is not None and is_main:
        # the trace is self-contained: scores/phases/part-loads ride in
        # the same JSONL as the span tree (Tracer.emit is MetricsWriter-
        # compatible, so the one record-set implementation serves both)
        from sheep_tpu.utils.metrics import emit_run_metrics

        for r in results:
            emit_run_metrics(tracer, r, n, wall, graph=args.input)

    if not is_main:
        return 0
    if not args.json:
        print(f"graph: {args.input}  V={n:,}  E={m:,}")
        print(f"backend: {res.backend}  k={','.join(str(k) for k in ks)}")
        for phase, secs in res.phase_times.items():
            print(f"  {phase:>16}: {secs:.3f}s")
        for r in results:
            print(f"k={r.k}: edge cut {r.edge_cut:,} "
                  f"({100 * r.cut_ratio:.2f}%)  balance {r.balance:.4f}"
                  + (f"  comm volume {r.comm_volume:,}"
                     if r.comm_volume is not None else ""))
            if args.output:
                print(f"partition map written to {_out_path(r.k)}")
        print(f"wall: {wall:.2f}s  "
              f"({m / wall if wall > 0 else 0:,.0f} edges/s)")
    # JSON result lines LAST, one per k — consumers parse the tail.
    # Multi-k wall accounting: extra ks carry their MARGINAL cost (their
    # split + scoring share), the first k the remainder — rows sum to
    # the run wall instead of over-counting it len(ks) times.
    marginal = {r.k: sum(r.phase_times.values()) for r in results[1:]}
    for r in results:
        summary = r.summary()
        r_wall = marginal.get(r.k, wall - sum(marginal.values()))
        summary["wall_seconds"] = round(r_wall, 4)
        summary["edges_per_sec"] = round(m / r_wall, 1) if r_wall > 0 \
            else None
        summary["n_vertices"] = n
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
