"""Multi-tenant job queue + admission + the interleaving dispatch loop.

The scheduler owns every job from submit to terminal state:

**Admission (membudget-aware).** Each job's device footprint is
modeled up front with the same HBM model the backends auto-size
against (``utils/membudget.build_phase_bytes`` at the job's resolved
dispatch batch). Against the daemon's budget (``SHEEP_CACHE_BYTES``
override, else 90% of reported HBM, else unlimited on cpu-jax):

- a job that exceeds the WHOLE budget is first shed down the same
  degradation schedule an OOM would force (``membudget
  .degraded_dispatch`` — halve the batch while the model says that
  frees the most), and REJECTED only if it still cannot fit at the
  fully degraded shape;
- a job that fits the budget but not the current free headroom stays
  QUEUED until earlier jobs release their reservation;
- admitted jobs reserve their modeled bytes until terminal.

**Interleave.** Admitted jobs step round-robin on one thread: each
step is one staged group of device work
(:class:`~sheep_tpu.server.engine.JobEngine`), so segments from
different jobs alternate on one dispatch chain, each folding into its
own carried table (order-independence of each job's fixpoint in its
own constraint multiset makes this sound — and
tests/test_server.py pins interleaved == solo bit-equality).

**Warm programs.** The hot jitted entry points are module-level jit
caches; the scheduler snapshots their compile-cache sizes around every
job, so a served response can PROVE warm reuse (``jit_compiles == 0``
for a repeat shape) — the 8-13 s cold warm-up the daemon exists to
amortize (BENCH_r03-r05).

**Deadlines / cancellation.** Both are scheduler-side cuts between
steps: the job's step generator is closed (unwinding through the
engine's ``finally`` blocks — prefetch workers cancel via
``Prefetcher.close``, phase spans end) and only that job changes
state; the dispatch chain and every other job's table are untouched.

**Durability (ISSUE 14).** With a journal configured
(:mod:`sheep_tpu.server.journal`), every job is write-ahead logged
submit->terminal (fsync at admission and terminal) and gets a per-job
:class:`~sheep_tpu.utils.checkpoint.Checkpointer` domain under
``checkpoint_dir``; the constructor replays the prior incarnation's
journal, re-admitting queued jobs and resuming running ones from
their checkpoints (bit-identical — the engine re-folds the remaining
chunks into the restored carried table). ``reattach_or_submit`` makes
retried client submits idempotent by spec digest;
``shutdown_suspend`` is the graceful drain: checkpoint each running
job at its next flush barrier, journal the handoff, exit with zero
unclosed spans. ``sheepd_restarts_total`` / ``sheepd_jobs_resumed_total``
surface the lineage at /metrics.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from sheep_tpu import obs
from sheep_tpu.obs.flightrec import FlightRecorder
from sheep_tpu.obs.metrics import MetricRegistry
from sheep_tpu.server import journal as journal_mod
from sheep_tpu.server import protocol
from sheep_tpu.server.engine import JobEngine
from sheep_tpu.server.protocol import (CANCELLED, DEADLINE_EXCEEDED, DONE,
                                       FAILED, QUEUED, REJECTED, RUNNING,
                                       TERMINAL_STATES, JobSpec)


def _hot_programs():
    """The jitted entry points whose per-process compile caches ARE the
    daemon's warm state (one cache entry per distinct shape/static
    combination)."""
    from sheep_tpu.ops import degrees as degrees_ops
    from sheep_tpu.ops import elim as elim_ops
    from sheep_tpu.ops import order as order_ops
    from sheep_tpu.ops import score as score_ops

    return {
        "fold_segments_batch_pos": elim_ops.fold_segments_batch_pos,
        "fold_segments_batch_pos_donated":
            elim_ops.fold_segments_batch_pos_donated,
        "orient_chunks_batch_pos": elim_ops.orient_chunks_batch_pos,
        "degree_chunk": degrees_ops.degree_chunk,
        "elimination_order": order_ops.elimination_order,
        "score_chunk": score_ops.score_chunk,
    }


def compile_cache_sizes() -> dict:
    """{program: compiled-variant count} for the hot programs — the
    warm-reuse evidence (a repeat shape adds zero everywhere)."""
    out = {}
    for name, fn in _hot_programs().items():
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = -1  # jit internals changed; counter degraded
    return out


def resolve_budget_bytes(budget_bytes: Optional[int] = None):
    """The daemon's admission budget: an explicit flag wins, then the
    ``SHEEP_CACHE_BYTES`` override (the documented HBM-budget knob),
    then 90% of the accelerator's reported HBM; None = unlimited
    (cpu-jax, where "device" memory is host RAM and the model would
    gate nothing real)."""
    if budget_bytes is not None:
        return int(budget_bytes) if budget_bytes > 0 else None
    env = os.environ.get("SHEEP_CACHE_BYTES")
    if env is not None:
        try:
            val = int(env)
        except ValueError:
            val = 0
        if val > 0:
            return val
        # SHEEP_CACHE_BYTES=0 means "spend nothing on the chunk cache"
        # everywhere else (tpu_backend._chunk_cache_budget) — for
        # admission it must NOT mean "unlimited"; fall through to the
        # platform default instead
    import jax

    if jax.default_backend() == "cpu":
        return None
    from sheep_tpu.backends.tpu_backend import _device_hbm_bytes

    hbm = _device_hbm_bytes(purpose="the admission budget")
    return int(0.9 * hbm) if hbm > 0 else None


class Job:
    """One submitted job: spec + lifecycle + results. State transitions
    happen only under the scheduler's lock."""

    def __init__(self, job_id: str, spec: JobSpec, n_vertices: int,
                 modeled_bytes: Optional[int]):
        self.id = job_id
        self.spec = spec
        self.state = QUEUED
        self.error: Optional[str] = None
        self.submit_t = time.time()
        self.start_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.deadline_t = None if spec.deadline_s is None \
            else self.submit_t + spec.deadline_s
        self.n_vertices = n_vertices
        self.modeled_bytes = modeled_bytes
        self.stats: dict = {}
        self.results: Optional[list] = None
        self.gen = None           # the engine step generator, once running
        self.span = None          # detached obs span for the job tree
        self.span_id = None
        # propagated wire trace context (ISSUE 18): the client-minted
        # 32-hex trace id + the client's 16-hex parent span id. The
        # job span starts with these as remote_parent attrs and the
        # flight ring learns the trace id, so one trace id correlates
        # this replica's spans/dumps with the client's route spans.
        self.trace_id: Optional[str] = None
        self.trace_parent: Optional[str] = None
        self.cancel_requested = False
        self.steps = 0
        # live phase name (degrees/sort/build/split/score): written by
        # the engine at phase entry and confirmed by the scheduler from
        # the step generator's yield values — the per-job progress
        # signal `sheep-submit --watch` and the job gauges poll
        self.phase: Optional[str] = None
        # per-step compile-cache delta sum (None until started): the
        # dispatch thread serializes steps, so attributing each step's
        # global cache growth to the job that ran it is EXACT even
        # under interleaving — a finalize-time delta would blame one
        # job for every concurrent job's compiles
        self.jit_compiles: Optional[int] = None
        # the engine shed the shared chunk cache under memory pressure;
        # the scheduler drops the cache entry at finalize so the HBM is
        # released and future jobs start a fresh cache
        self.cache_shed = False
        # admitted in spilled mode (ISSUE 20): over the budget at every
        # dispatch shape, so it runs at the irreducible floor — no
        # shared chunk cache lease, every overlap knob at 1
        self.spilled = False
        # ---- durability (ISSUE 14) -----------------------------------
        # deterministic submit identity (spec + input content), the
        # reattach key; journaled at submit
        self.digest: Optional[str] = None
        # per-job Checkpointer domain + the live engine (the graceful
        # drain's request_checkpoint handle), set at start
        self.ckpt = None
        self.engine = None
        # True once a graceful drain parked this job with its state on
        # disk (non-terminal: the journal replays it as resumable)
        self.suspended = False
        # a job replayed as terminal from the journal carries result
        # SUMMARIES only (assignment arrays are not journaled)
        self.replayed_results: Optional[list] = None
        # ---- resident partition (ISSUE 15) ---------------------------
        # the engine parks the finished build's incremental state here
        # (spec.resident only); finalize adopts it as resident_state,
        # which update/epoch/compact verbs then mutate on the dispatch
        # thread. A restarted daemon reloads it lazily from the
        # resident-state snapshot; journaled_epoch is the journal's
        # floor for the resumed epoch.
        self.incremental_state = None
        self.resident_state = None
        self.resident_released = False
        self.journaled_epoch = 0
        self._upd_backend = None

    def journal_spec(self) -> dict:
        import dataclasses

        return dataclasses.asdict(self.spec)

    def descriptor(self, with_results: bool = False) -> dict:
        d = {"job_id": self.id, "tenant": self.spec.tenant,
             "input": self.spec.input, "k": list(self.spec.ks),
             "state": self.state, "submit_t": round(self.submit_t, 3),
             "n_vertices": int(self.n_vertices),
             "modeled_bytes": self.modeled_bytes, "steps": self.steps}
        if self.phase is not None:
            d["phase"] = self.phase
        if self.error is not None:
            d["error"] = self.error
        if self.deadline_t is not None:
            d["deadline_t"] = round(self.deadline_t, 3)
        if self.start_t is not None:
            d["start_t"] = round(self.start_t, 3)
        if self.end_t is not None:
            d["end_t"] = round(self.end_t, 3)
            base = self.start_t if self.start_t is not None \
                else self.submit_t
            d["wall_s"] = round(self.end_t - base, 4)
        if self.jit_compiles is not None:
            d["jit_compiles"] = self.jit_compiles
        if self.spec.resident:
            d["resident"] = not self.resident_released
            st = self.resident_state
            d["epoch"] = int(st.epoch) if st is not None \
                else int(self.journaled_epoch)
        if self.state == DONE and self.results is not None:
            d["results"] = []
            for r in self.results:
                row = r.summary()
                if with_results and self.spec.return_assignment:
                    row["assignment"] = protocol.encode_assignment(
                        r.assignment)
                d["results"].append(row)
        elif self.state == DONE and self.replayed_results is not None:
            # journal-replayed completion: scores survive the restart,
            # assignment payloads do not (use job.output for those)
            d["results"] = [dict(row) for row in self.replayed_results]
        return d


class Scheduler:
    """See module docstring. Thread model: any number of submitter
    threads (the daemon's connection handlers) call submit/cancel/wait;
    ONE dispatch thread calls :meth:`run`. All shared state is guarded
    by ``self._lock`` (the condition's lock)."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 root_span_id=None, journal=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 16, result_store=None):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.budget = resolve_budget_bytes(budget_bytes)
        self.root_span_id = root_span_id
        self._jobs: "OrderedDict[str, Job]" = OrderedDict()
        self._pending: deque = deque()
        self._active: deque = deque()   # admitted; round-robin order
        self._ids = itertools.count(1)
        self._stop = False
        self._draining = False
        # resident-partition work items (update/epoch/compact verbs,
        # ISSUE 15): handler threads enqueue + wait, the ONE dispatch
        # thread executes — delta folds share the dispatch chain with
        # job steps, never a second thread on the device
        self._updates: deque = deque()
        # ---- durability (ISSUE 14): crash-safe journal + per-job
        # checkpoint domains. journal is a JobJournal or a path; with
        # one set, every job is journaled submit->terminal and the
        # constructor REPLAYS the prior incarnation's journal:
        # journaled queued jobs re-enter the queue, journaled running
        # jobs re-enter it flagged resumable (their engines resume
        # from the per-job checkpoints under checkpoint_dir), and
        # terminal jobs stay queryable with their journaled scores.
        self.journal = None
        self.ckpt_dir = checkpoint_dir
        self.ckpt_every = max(1, int(checkpoint_every))
        self._suspending = False
        self._suspend_deadline = 0.0
        self._restarts = 0
        self._caches: "OrderedDict[tuple, dict]" = OrderedDict()
        # ---- fleet warm path (ISSUE 16): content-addressed result
        # store. A repeat submit whose digest hits answers DONE at
        # admission — zero dispatch steps, zero recompiles, the exact
        # packed assignment the original build produced. Accepts a
        # ResultStore or a directory path.
        if isinstance(result_store, str):
            from sheep_tpu.server.resultstore import ResultStore

            result_store = ResultStore(result_store)
        self.result_store = result_store
        self._rc_evictions_seen = 0
        self.totals = {"submitted": 0, "done": 0, "failed": 0,
                       "cancelled": 0, "rejected": 0,
                       "deadline_exceeded": 0}
        self.started_t = time.time()
        # ---- live telemetry plane (ISSUE 11) -------------------------
        # Typed metric registry: the `metrics` verb and the daemon's
        # HTTP /metrics listener render this; the collector absorbs
        # queue/reservation/cache state, per-active-job progress, the
        # active tracer's CounterRegistry and device memory as live
        # gauges at scrape time.
        self.metrics = MetricRegistry()
        self._m_submitted = self.metrics.counter(
            "sheepd_jobs_submitted_total",
            "jobs accepted at the protocol boundary", ("tenant",))
        self._m_terminal = self.metrics.counter(
            "sheepd_jobs_terminal_total",
            "jobs reaching a terminal state", ("tenant", "state"))
        self._m_rejected = self.metrics.counter(
            "sheepd_admission_rejected_total",
            "jobs the admission budget rejected outright", ("tenant",))
        self._m_retries = self.metrics.counter(
            "sheepd_dispatch_retries_total",
            "dispatch retries absorbed inside served jobs", ("tenant",))
        self._m_steps = self.metrics.counter(
            "sheepd_steps_total",
            "dispatch steps executed (one staged group of device work)",
            ("tenant",))
        self._m_latency = self.metrics.histogram(
            "sheepd_request_latency_seconds",
            "queued->done request latency (the SLO series)", ("tenant",))
        self._m_queue_wait = self.metrics.histogram(
            "sheepd_queue_wait_seconds",
            "submit->start admission wait", ("tenant",))
        self._m_step_s = self.metrics.histogram(
            "sheepd_step_seconds", "one dispatch step", ("phase",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
        # ---- durability plane (ISSUE 14): restart visibility --------
        self._m_restarts = self.metrics.counter(
            "sheepd_restarts_total",
            "daemon restarts observed in this journal lineage "
            "(prior daemon_start records at replay)")
        self._m_resumed = self.metrics.counter(
            "sheepd_jobs_resumed_total",
            "journaled RUNNING jobs re-admitted at startup to resume "
            "from their checkpoints")
        self._m_reattached = self.metrics.counter(
            "sheepd_submits_reattached_total",
            "idempotent resubmissions matched to an existing job by "
            "digest", ("tenant",))
        # ---- fleet plane (ISSUE 16): result-cache visibility --------
        self._m_rc_hits = self.metrics.counter(
            "sheepd_result_cache_hits_total",
            "submits answered from the content-addressed result store "
            "(zero build steps, zero recompiles)", ("tenant",))
        self._m_rc_misses = self.metrics.counter(
            "sheepd_result_cache_misses_total",
            "submits that probed the result store and built", ("tenant",))
        self._m_rc_evictions = self.metrics.counter(
            "sheepd_result_cache_evictions_total",
            "result-store entries evicted oldest-first under the "
            "byte cap")
        # ---- incremental plane (ISSUE 15): resident partitions ------
        self._m_updates = self.metrics.counter(
            "sheep_updates_total",
            "delta epochs applied to resident partitions", ("tenant",))
        self._m_update_latency = self.metrics.histogram(
            "sheep_update_latency_seconds",
            "one update verb: fold + (optional) refresh wall",
            ("tenant",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
        self._m_compactions = self.metrics.counter(
            "sheep_compactions_total",
            "resident-partition compactions (tombstone repair)",
            ("tenant", "mode"))
        # ---- O(delta) plane (ISSUE 17): streamed epochs + fairness --
        self._m_update_throttled = self.metrics.counter(
            "sheepd_update_throttled_total",
            "update items deferred to a later dispatch cycle by the "
            "per-tenant byte budget", ("tenant",))
        self._m_update_score = self.metrics.histogram(
            "sheepd_update_score_seconds",
            "scored-refresh wall per update epoch (incremental "
            "rescoring makes this O(delta), not O(edges))",
            ("tenant",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
        # ---- quality plane (ISSUE 13): partition QUALITY is a live,
        # scrapeable series, not just a number in a result payload —
        # per-tenant cut/balance distributions at DONE, plus per-job
        # gauges for recent results via the collector below, so a
        # fleet dashboard catches "this tenant's cuts got worse" the
        # same way it catches latency regressions.
        from sheep_tpu.obs.metrics import (DEFAULT_BALANCE_BUCKETS,
                                           DEFAULT_RATIO_BUCKETS)

        self._m_quality_cut = self.metrics.histogram(
            "sheep_quality_cut_ratio",
            "final cut ratio of DONE jobs, one observation per "
            "result k", ("tenant",), buckets=DEFAULT_RATIO_BUCKETS)
        self._m_quality_balance = self.metrics.histogram(
            "sheep_quality_balance",
            "final balance of DONE jobs, one observation per result k",
            ("tenant",), buckets=DEFAULT_BALANCE_BUCKETS)
        # ---- fleet observability plane (ISSUE 18): the SLO layer's
        # missing denominator — every answered wire request by verb
        # and outcome (tools/slo_check.py divides error outcomes by
        # the total for the error-rate bound)
        self._m_requests = self.metrics.counter(
            "sheepd_requests_total",
            "wire requests answered, by verb and outcome (ok|error)",
            ("verb", "outcome"))
        self.metrics.add_collector(self._collect_live_gauges)
        # Always-on flight recorder: bounded per-job rings fed by
        # obs.event, dumped on job failure / fault injection / shutdown
        # — post-mortem forensics without full tracing on every request
        self.flight = obs.install_flight(FlightRecorder())
        # on-demand jax.profiler capture state (the `profile` verb):
        # armed under the lock, driven by the dispatch thread only
        self._profile: Optional[dict] = None
        self.last_profile: Optional[dict] = None
        if journal is not None:
            self._recover(journal)

    # ------------------------------------------------------------------
    # durability: journal replay at startup (ISSUE 14)
    # ------------------------------------------------------------------
    def _recover(self, journal) -> None:
        """Open (or adopt) the journal, replay the prior incarnation's
        records, and re-seed the queue: queued jobs re-admit as
        submitted, running jobs re-admit flagged resumable (their
        engines resume from the per-job checkpoints), terminal jobs
        stay queryable with journaled scores. Runs in the constructor
        — before any handler thread exists; the lock is uncontended
        but keeps every shared-state mutation lexically guarded."""
        with self._lock:
            if isinstance(journal, str):
                journal = journal_mod.JobJournal(journal)
            self.journal = journal
            replay = journal.replay()
            self._restarts = replay.daemon_starts
            resumed = 0
            for rj in replay.jobs:
                try:
                    spec = JobSpec(
                        **{k: v for k, v in rj.spec.items()
                           if k in JobSpec.__dataclass_fields__})
                except (TypeError, ValueError) as e:
                    journal_mod._warn(
                        f"journaled spec of {rj.job_id} does not "
                        f"reconstruct ({type(e).__name__}: {e}); "
                        f"dropped")
                    continue
                job = Job(rj.job_id, spec, rj.n_vertices,
                          rj.modeled_bytes)
                job.digest = rj.digest
                job.submit_t = rj.submit_t
                # resident lineage (ISSUE 15): the journal's epoch
                # floor; the state snapshot (>= this epoch — it is
                # saved BEFORE the journal record) loads lazily on
                # the first update/epoch/compact touch
                job.journaled_epoch = rj.delta_epoch
                job.resident_released = rj.resident_released
                job.deadline_t = None if spec.deadline_s is None \
                    else rj.submit_t + spec.deadline_s
                self._jobs[job.id] = job
                self.totals["submitted"] += 1
                if rj.terminal:
                    job.state = rj.state
                    job.error = rj.error
                    job.end_t = rj.end_t
                    job.replayed_results = rj.results
                    self.totals[rj.state] = \
                        self.totals.get(rj.state, 0) + 1
                else:
                    # both queued and running replay into the queue; a
                    # running job's per-job checkpoint dir makes its
                    # restart a RESUME, not a rebuild (and a running
                    # job that never checkpointed degrades to a clean
                    # start — the graceful fallback, never a loss of
                    # the job)
                    job.state = QUEUED
                    self._pending.append(job)
                    if rj.state == RUNNING:
                        resumed += 1
                        job.stats["journal_resumed"] = 1
                obs.event("job_recovered", job=job.id,
                          tenant=spec.tenant, state=job.state,
                          journaled_state=rj.state)
            if replay.jobs or replay.daemon_starts:
                import sys

                print(f"sheepd: journal replayed {len(replay.jobs)} "
                      f"job(s) ({len(self._pending)} re-admitted, "
                      f"{resumed} resumable) after "
                      f"{replay.daemon_starts} prior start(s)",
                      file=sys.stderr, flush=True)
            self._ids = itertools.count(replay.next_id)
            if replay.daemon_starts:
                self._m_restarts.inc(replay.daemon_starts)
            if resumed:
                self._m_resumed.inc(resumed)
            journal.append({"rec": "daemon_start", "t": time.time(),
                            "pid": os.getpid()}, fsync=True)

    # ------------------------------------------------------------------
    # submit-side API (connection handler threads)
    # ------------------------------------------------------------------
    def submit(self, spec: JobSpec, digest: Optional[str] = None,
               trace=None) -> Job:
        """Validate + model + enqueue. Raises ProtocolError on inputs
        that cannot be opened (answered ok=false; no job is created) —
        admission-budget verdicts come back as a REJECTED job instead,
        so they are queryable like any other terminal state. ``digest``
        lets reattach_or_submit hand over the identity it already
        computed (and matched against) instead of hashing twice.
        ``trace`` is the request's parsed wire trace context — a
        ``(trace_id, parent_span)`` pair (ISSUE 18) — threaded into
        the job span and flight ring."""
        if digest is None:
            digest = journal_mod.job_digest(spec)
        n = self._probe_num_vertices(spec)
        modeled, batch, rejected_why, spilled = self._model(spec, n)
        hit = None
        if self.result_store is not None and not spec.resident:
            # fleet warm path (ISSUE 16): a digest hit answers DONE
            # from the store before admission ever reserves device
            # memory. Resident jobs never consult the store — their
            # value is the carried incremental state, which a cached
            # answer lacks. The read happens OFF-lock (file IO).
            try:
                hit = self.result_store.get(digest)
            except ValueError as e:
                # strict IO policy: a damaged entry refuses to serve —
                # this submit fails loudly instead of silently
                # rebuilding (quarantine policy reports a miss instead)
                raise protocol.ProtocolError(str(e)) from None
        with self._lock:
            if self._stop or self._draining or self._suspending:
                raise protocol.ProtocolError("daemon is shutting down")
            job = Job(f"j{next(self._ids)}", spec, n, modeled)
            job.digest = digest
            if trace is not None:
                job.trace_id, job.trace_parent = trace
                self.flight.set_trace(job.id, job.trace_id)
            # the admission pre-shed: run at the degraded batch that
            # fits (the same knob an OOM would halve mid-run)
            if batch is not None and batch != spec.dispatch_batch:
                job.spec.dispatch_batch = batch
                job.stats["admission_dispatch_batch"] = batch
            if spilled:
                # over-budget job admitted in spilled mode (ISSUE 20):
                # every overlap knob pinned to 1 and NO shared chunk
                # cache lease — the floor the admission model priced
                job.spec.dispatch_batch = 1
                job.spec.inflight = 1
                job.spec.h2d_ring = 1
                job.spilled = True
                job.stats["admission_spilled"] = 1
            self._jobs[job.id] = job
            self.totals["submitted"] += 1
            self._m_submitted.inc(tenant=spec.tenant)
            if hit is not None:
                pass  # served from the store after the submit WAL below
            elif rejected_why is not None:
                job.state = REJECTED
                job.error = rejected_why
                job.end_t = time.time()
                self.totals["rejected"] += 1
                self._m_rejected.inc(tenant=spec.tenant)
                self._m_terminal.inc(tenant=spec.tenant, state=REJECTED)
            else:
                if self.result_store is not None and not spec.resident:
                    self._m_rc_misses.inc(tenant=spec.tenant)
                self._pending.append(job)
            if self.journal is not None:
                # the WAL's admission promise: once the client holds
                # this job id, a crash cannot lose the job (fsync'd
                # BEFORE the response leaves; the pre-shed spec is
                # journaled so the replayed run models identically)
                self.journal.append(
                    {"rec": "submit", "job_id": job.id,
                     "t": job.submit_t, "tenant": spec.tenant,
                     "digest": digest, "n_vertices": int(n),
                     "modeled_bytes": modeled, "state": job.state,
                     **({"error": job.error} if job.error else {}),
                     "spec": job.journal_spec()}, fsync=True)
            obs.event("job_submit", job=job.id, tenant=spec.tenant,
                      input=spec.input, k=list(spec.ks), state=job.state,
                      modeled_bytes=modeled,
                      **({"trace": job.trace_id}
                         if job.trace_id else {}))
            if hit is not None:
                self._serve_from_store_locked(job, hit)
            self._cond.notify_all()
            return job

    def _serve_from_store_locked(self, job: Job, entry: dict) -> None:
        """Adopt a result-store hit as this job's DONE terminal
        (ISSUE 16): reconstruct the PartitionResult rows from the
        stored summaries + packed assignments (bit-identical — the
        store kept the exact payload the original build answered),
        then run the normal finalize: terminal WAL, output write,
        quality series, retention. Zero dispatch steps and zero jit
        compiles by construction — the job never enters the queue."""
        from sheep_tpu.types import PartitionResult

        results = []
        for row in entry.get("results") or []:
            results.append(PartitionResult(
                assignment=protocol.decode_assignment(row["assignment"]),
                k=int(row["k"]), edge_cut=int(row["edge_cut"]),
                total_edges=int(row["total_edges"]),
                cut_ratio=float(row["cut_ratio"]),
                balance=float(row["balance"]),
                comm_volume=row.get("comm_volume"),
                phase_times=dict(row.get("phase_times") or {}),
                backend=str(row.get("backend", "sheepd")),
                diagnostics=dict(row.get("diagnostics") or {})))
        job.results = results
        job.jit_compiles = 0
        job.stats["result_cache_hit"] = 1
        self._m_rc_hits.inc(tenant=job.spec.tenant)
        obs.event("result_cache_hit", job=job.id,
                  tenant=job.spec.tenant, digest=job.digest,
                  **({"trace": job.trace_id} if job.trace_id else {}))
        self._finalize_locked(job, DONE)

    def reattach_or_submit(self, spec: JobSpec, trace=None):
        """Idempotent resubmission (ISSUE 14): match the spec's digest
        against existing jobs and return ``(job, True)`` for a live or
        completed twin instead of double-building — the contract a
        client's retried submit leans on across a daemon restart. A
        failed/cancelled/rejected twin does NOT match (retrying those
        is exactly what a fresh submit is for). The check-then-submit
        window is unlocked (submit probes the input off-lock), so two
        simultaneous first-time reattach submits may both build — the
        retried-client scenario this exists for is serial.

        A matched twin with no trace of its own ADOPTS the retried
        request's trace context (ISSUE 18): a failover resubmit that
        reattaches to a journal-replayed job still names the fleet
        request in that replica's trace and flight dumps."""
        digest = journal_mod.job_digest(spec)
        with self._lock:
            for job in reversed(self._jobs.values()):
                if job.digest == digest \
                        and job.state in (QUEUED, RUNNING, DONE):
                    if trace is not None and job.trace_id is None:
                        job.trace_id, job.trace_parent = trace
                        self.flight.set_trace(job.id, job.trace_id)
                        if job.span is not None:
                            job.span.annotate(
                                trace=job.trace_id,
                                **({"remote_parent": job.trace_parent}
                                   if job.trace_parent else {}))
                    self._m_reattached.inc(tenant=spec.tenant)
                    obs.event("job_reattach", job=job.id,
                              tenant=spec.tenant, state=job.state,
                              **({"trace": job.trace_id}
                                 if job.trace_id else {}))
                    return job, True
        return self.submit(spec, digest=digest, trace=trace), False

    def record_request(self, verb: str, outcome: str) -> None:
        """Tally one answered wire request into
        ``sheepd_requests_total{verb,outcome}`` (ISSUE 18) — the
        error-rate numerator/denominator the SLO gate reads. Called by
        the daemon's connection handlers; label values are free-form
        but bounded in practice (verb comes from protocol.OPS or
        "malformed", outcome is ok|error)."""
        self._m_requests.inc(verb=str(verb), outcome=str(outcome))

    def _probe_num_vertices(self, spec: JobSpec) -> int:
        from sheep_tpu.io.edgestream import open_input

        try:
            with open_input(spec.input,
                            n_vertices=spec.num_vertices) as es:
                return int(es.num_vertices)
        except Exception as e:
            raise protocol.ProtocolError(
                f"cannot open job input {spec.input!r}: "
                f"{type(e).__name__}: {str(e)[:200]}") from None

    def _model(self, spec: JobSpec, n: int):
        """(modeled_bytes, pre-shed dispatch_batch or None, reject
        reason or None, spilled bool) for admission. Models at the
        REQUESTED chunk size (clamping only shrinks it —
        conservative), with the same staged-H2D-ring term the engine
        will actually run (ISSUE 12): device-stream inputs stage
        nothing, host-format ones hold ring x batch blocks in HBM —
        reserving without that term would admit jobs whose real
        footprint exceeds the budget and re-create the OOM churn
        admission exists to prevent.

        Spilled-mode admission (ISSUE 20): a job the halving ladder
        cannot fit even at dispatch_batch=1 is admitted at the
        IRREDUCIBLE floor — batch=1, inflight=1, ring depth 1, zero
        resident chunk bytes (the engine runs without the shared chunk
        cache; every pass streams from disk) — instead of rejected.
        The build is bit-identical at any dispatch shape (the fixpoint
        invariant), so spilled mode trades only wall time for
        admission. Rejection remains only for jobs whose floor itself
        exceeds the budget."""
        from sheep_tpu.backends.tpu_backend import (resolve_dispatch_batch,
                                                    resolve_h2d_ring,
                                                    resolve_inflight)
        from sheep_tpu.io.devicestream import is_device_stream
        from sheep_tpu.io.edgestream import open_input
        from sheep_tpu.utils import membudget

        cs = spec.chunk_edges
        try:
            with open_input(spec.input,
                            n_vertices=spec.num_vertices) as es:
                dev_stream = is_device_stream(es)
        except Exception:
            dev_stream = False  # _probe_num_vertices already rejected
        ring = 0 if dev_stream else resolve_h2d_ring(spec.h2d_ring)
        # the in-job pipeline (ISSUE 16) keeps D issued executions'
        # staging blocks live at once — admission must reserve them or
        # a full pipe re-creates the OOM churn it exists to prevent
        infl = resolve_inflight(spec.inflight)
        batch = resolve_dispatch_batch(spec.dispatch_batch, n, cs,
                                       inflight=infl, h2d_ring=ring)
        if self.budget is None:
            return None, None, None, False

        def total(b):
            return membudget.build_phase_bytes(
                n, cs, dispatch_batch=b, inflight=infl,
                h2d_ring=ring)["total_bytes"]

        m = total(batch)
        shed = None
        while m > self.budget:
            nxt = membudget.degraded_dispatch(n, cs, batch, 1)
            if nxt is None:
                # spilled mode: the irreducible footprint — every
                # overlap knob at 1, nothing resident (resident_bytes
                # names the term it zeroes: the job runs cache-less,
                # streaming each pass from the disk tier)
                floor = membudget.build_phase_bytes(
                    n, cs, dispatch_batch=1, inflight=1,
                    h2d_ring=min(1, ring),
                    resident_bytes=0)["total_bytes"]
                if floor <= self.budget:
                    return floor, 1, None, True
                return m, None, (
                    f"modeled device footprint {m:,} bytes exceeds the "
                    f"admission budget {self.budget:,} even spilled "
                    f"(floor {floor:,} at dispatch_batch=1, inflight=1 "
                    f"with nothing resident; V={n:,}, "
                    f"chunk_edges={cs:,}); shrink the graph/chunk or "
                    f"raise the budget"), False
            batch = nxt[0]
            shed = batch
            m = total(batch)
        return m, shed, None, False

    @staticmethod
    def _is_resident(job: Job) -> bool:
        """A DONE resident job whose partition is still held — its
        modeled bytes stay charged to the admission budget (the
        resident state re-enters device memory on every update fold),
        until the tenant releases it via cancel."""
        return (job.spec.resident and job.state == DONE
                and not job.resident_released)

    def _reserved_locked(self) -> int:
        with self._lock:
            active = sum(j.modeled_bytes or 0 for j in self._active)
            resident = sum(j.modeled_bytes or 0
                           for j in self._jobs.values()
                           if self._is_resident(j))
            return active + resident

    def get(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.values())

    def cancel(self, job_id: str) -> Optional[str]:
        """Request cancellation; returns the job's (possibly already
        terminal) state, or None for an unknown id. A queued job is
        finalized immediately — cancellation FREES THE QUEUE without
        waiting for a dispatch cycle. A RUNNING job's cancel is
        asynchronous (the returned state is still ``running``): the
        dispatch loop finalizes it before its next step — observe the
        terminal state with :meth:`wait`."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job.state in TERMINAL_STATES:
                if self._is_resident(job):
                    # cancel on a DONE resident job RELEASES the
                    # residency: reservation freed, state dropped,
                    # snapshot removed, release journaled (replay
                    # must not re-charge the budget)
                    job.resident_released = True
                    job.resident_state = None
                    job.incremental_state = None
                    path = self._resident_path(job.id)
                    if path is not None:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                    if self.journal is not None:
                        self.journal.append(
                            {"rec": "resident_release",
                             "job_id": job.id, "t": time.time()},
                            fsync=True)
                    obs.event("resident_release", job=job.id,
                              tenant=job.spec.tenant)
                    self._cond.notify_all()
                return job.state
            if job.state == QUEUED:
                try:
                    self._pending.remove(job)
                except ValueError:
                    pass
                self._finalize_locked(job, CANCELLED)
            else:
                job.cancel_requested = True
                self._cond.notify_all()
            return job.state

    def wait(self, job_id: str, timeout_s: Optional[float] = None):
        """Block until the job is terminal (or timeout); returns the
        Job, or None for an unknown id."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._lock:
            while True:
                job = self._jobs.get(job_id)
                if job is None or job.state in TERMINAL_STATES:
                    return job
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return job
                self._cond.wait(timeout=0.1 if remaining is None
                                else min(0.1, remaining))

    def stats(self) -> dict:
        with self._lock:
            by_state: dict = {}
            for job in self._jobs.values():
                by_state[job.state] = by_state.get(job.state, 0) + 1
            reserved = self._reserved_locked()
            resident = sum(1 for j in self._jobs.values()
                           if self._is_resident(j))
            return {
                "uptime_s": round(time.time() - self.started_t, 1),
                "budget_bytes": self.budget,
                "reserved_bytes": reserved,
                "resident_partitions": resident,
                "durable": self.journal is not None,
                "restarts": self._restarts,
                "jobs": dict(self.totals),
                "jobs_by_state": by_state,
                "queued": len(self._pending),
                "active": len(self._active),
                "compile_cache": compile_cache_sizes(),
                "chunk_caches": len(self._caches),
                "flight_dumps": self.flight.dumps,
                # a COPY, internals stripped: the live dict is mutated
                # by the dispatch thread while a handler serializes
                "profile": (None if (self._profile or self.last_profile)
                            is None else
                            {k: v for k, v in
                             (self._profile
                              or self.last_profile).items()
                             if k != "remaining"}),
            }

    def shutdown(self, drain: bool = False) -> None:
        """Stop the dispatch loop. ``drain`` finishes the jobs already
        accepted first; otherwise every non-terminal job is cancelled
        on the next cycle (their spans close — a clean shutdown leaves
        ZERO unclosed spans)."""
        with self._lock:
            if drain:
                self._draining = True
            else:
                self._stop = True
            self._cond.notify_all()

    def shutdown_suspend(self, grace_s: float = 10.0) -> None:
        """Graceful drain (ISSUE 14, sheepd's SIGTERM): stop
        admitting, checkpoint each running job at its next flush
        barrier, journal the handoff, then let :meth:`run` return —
        running jobs stay NON-terminal (journal state ``running``), so
        the next incarnation resumes them where they parked. Queued
        jobs stay queued. Falls back to plain cancel-shutdown when the
        scheduler is not durable (nothing could resume them)."""
        with self._lock:
            if self.journal is None:
                self._stop = True
            elif not self._suspending:
                self._suspending = True
                self._suspend_deadline = \
                    time.monotonic() + max(0.0, float(grace_s))
                obs.event("daemon_suspend_begin",
                          grace_s=float(grace_s),
                          active=len(self._active),
                          queued=len(self._pending))
            self._cond.notify_all()

    def _park_locked(self, job: Job) -> None:
        """Suspend one running job with its state on disk: out of the
        round-robin, span ended (state=suspended — a graceful drain
        leaves zero unclosed spans), job NON-terminal. The generator
        unwind happens outside the lock, like every close."""
        with self._lock:
            try:
                self._active.remove(job)
            except ValueError:
                pass
            job.suspended = True
            job.engine = None
            if job.span is not None:
                job.span.end(state="suspended", steps=job.steps)
                job.span = None
            obs.event("job_suspend", job=job.id,
                      tenant=job.spec.tenant, steps=job.steps,
                      phase=job.phase)

    def _suspend_cycle(self) -> bool:
        """One dispatch-loop pass of the graceful drain: arm each
        active engine's next-barrier checkpoint, park the ones whose
        save landed (or everything, once the grace deadline passes),
        and keep stepping the rest. True = fully parked, journal the
        handoff, run() should return."""
        to_park = []
        step_more = []
        with self._lock:
            timed_out = time.monotonic() >= self._suspend_deadline
            for job in list(self._active):
                eng = job.engine
                if eng is not None and job.ckpt is not None \
                        and not timed_out:
                    eng.request_checkpoint()
                    if not eng.suspend_ready:
                        step_more.append(job)
                        continue
                # saved (or nothing to save / out of grace: the last
                # cadence checkpoint still makes restart a resume)
                to_park.append(job)
            for job in to_park:
                self._park_locked(job)
            done = not self._active
        for job in to_park:
            self._close_gen(job)
        if done:
            with self._lock:
                suspended = [j.id for j in self._jobs.values()
                             if j.suspended]
                queued = [j.id for j in self._pending]
                if self.journal is not None:
                    self.journal.append(
                        {"rec": "drain", "t": time.time(),
                         "suspended": suspended, "queued": queued},
                        fsync=True)
                obs.event("daemon_suspend_done",
                          suspended=len(suspended), queued=len(queued))
            return True
        for job in step_more:
            self._step(job)
        return False

    # ------------------------------------------------------------------
    # live telemetry (ISSUE 11): /metrics exposition + heartbeat feed
    # ------------------------------------------------------------------
    def render_metrics(self) -> str:
        """The Prometheus exposition document the `metrics` verb and
        the daemon's HTTP listener answer."""
        return self.metrics.render()

    def service_pressure(self) -> dict:
        """Cheap live queue-depth/active-job sample — the heartbeat's
        service-pressure fields when running inside sheepd."""
        with self._lock:
            return {"queue_depth": len(self._pending),
                    "active_jobs": len(self._active)}

    def _collect_live_gauges(self):
        """Scrape-time collector: queue/reservation/cache state,
        per-active-job progress, the active tracer's CounterRegistry
        absorbed as live gauges (not just span-boundary deltas), and
        device-memory stats. Runs on the scraping thread; everything
        under the lock is a handful of len()s."""
        with self._lock:
            active = list(self._active)
            residents = [j for j in self._jobs.values()
                         if self._is_resident(j)]
            samples = [
                ("sheepd_queue_depth", {}, len(self._pending)),
                ("sheepd_active_jobs", {}, len(active)),
                ("sheepd_reserved_bytes", {}, self._reserved_locked()),
                ("sheepd_resident_partitions", {}, len(residents)),
                ("sheepd_chunk_caches", {}, len(self._caches)),
                ("sheepd_uptime_seconds", {},
                 round(time.time() - self.started_t, 1)),
                # no _total suffix: collector samples render as gauges,
                # and a _total-named gauge trips OpenMetrics linting
                ("sheepd_flight_dumps", {}, self.flight.dumps),
            ]
            if self.budget is not None:
                reserved = self._reserved_locked()
                samples.append(("sheepd_budget_bytes", {}, self.budget))
                samples.append(("sheepd_headroom_bytes", {},
                                self.budget - reserved))
            for job in active:
                labels = {"job": job.id, "tenant": job.spec.tenant}
                samples.append(("sheepd_job_steps", labels, job.steps))
            for job in residents:
                st = job.resident_state
                samples.append(
                    ("sheepd_resident_epoch",
                     {"job": job.id, "tenant": job.spec.tenant},
                     int(st.epoch) if st is not None
                     else int(job.journaled_epoch)))
            # per-job quality gauges (ISSUE 13): the most recent DONE
            # jobs' final scores, scrapeable per job/tenant/k. Bounded
            # to the 32 newest COMPLETIONS (submit order would let a
            # long-queued early job push the one that just finished
            # out of the scrape) so a long-lived daemon's scrape does
            # not grow with terminal-retention history.
            done = sorted((j for j in self._jobs.values()
                           if j.state == DONE and j.results),
                          key=lambda j: j.end_t or 0.0)
            for job in done[-32:]:
                for r in job.results:
                    labels = {"job": job.id, "tenant": job.spec.tenant,
                              "k": str(r.k)}
                    samples.append(("sheep_quality_job_cut_ratio",
                                    labels, float(r.cut_ratio)))
                    samples.append(("sheep_quality_job_balance",
                                    labels, float(r.balance)))
        store = self.result_store
        if store is not None:
            # file IO (listdir + stat) — outside the lock by design
            samples.append(("sheepd_result_cache_bytes", {},
                            store.bytes_used))
        for name, n in compile_cache_sizes().items():
            samples.append(("sheepd_compile_cache_entries",
                            {"program": name}, n))
        tracer = obs.get_tracer()
        if tracer is not None:
            for k, v in tracer.counters.snapshot().items():
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    samples.append(("sheep_run_counter",
                                    {"name": str(k)}, v))
        from sheep_tpu.utils.metrics import device_memory_stats

        for k, v in (device_memory_stats() or {}).items():
            samples.append((f"sheepd_device_{k}", {}, v))
        return samples

    # ------------------------------------------------------------------
    # on-demand device profiling (the `profile` verb)
    # ------------------------------------------------------------------
    def arm_profile(self, profile_dir: str, steps: int = 8) -> dict:
        """Arm a jax.profiler capture of the next ``steps`` dispatch
        steps into ``profile_dir``. Returns the armed descriptor; the
        capture itself is driven by the dispatch thread (profiling a
        live daemon must not add a second thread touching the device).
        One capture at a time — overlapping captures would interleave
        in one trace directory and attribute nothing."""
        try:
            steps = int(steps)
        except (TypeError, ValueError):
            raise protocol.ProtocolError(
                "profile steps must be an integer") from None
        if steps < 1:
            raise protocol.ProtocolError("profile steps must be >= 1")
        with self._lock:
            if self._stop or self._draining:
                raise protocol.ProtocolError("daemon is shutting down")
            if self._profile is not None:
                raise protocol.ProtocolError(
                    "a profile capture is already "
                    f"{self._profile.get('state', 'armed')} "
                    f"(dir {self._profile.get('dir')!r})")
            self._profile = {"dir": str(profile_dir), "state": "armed",
                             "steps_requested": steps,
                             "remaining": steps}
            info = {k: v for k, v in self._profile.items()
                    if k != "remaining"}
        obs.event("profile_armed", dir=str(profile_dir), steps=steps)
        return info

    def _profile_tick_begin(self) -> None:
        # dispatch thread only (the sole state-transitioner once
        # armed): start the armed capture at a step boundary so the
        # trace holds WHOLE steps. Dict mutations happen under the
        # lock — stats() snapshots this dict from handler threads.
        prof = self._profile
        if prof is None or prof["state"] != "armed":
            return
        try:
            import jax

            jax.profiler.start_trace(prof["dir"])
        except Exception as e:  # profiler unavailable: verb answered,
            with self._lock:    # daemon unharmed
                prof["state"] = "error"
                prof["error"] = f"{type(e).__name__}: {str(e)[:200]}"
                self.last_profile = {k: v for k, v in prof.items()
                                     if k != "remaining"}
                self._profile = None
            obs.event("profile_error", dir=prof["dir"],
                      error=prof["error"])
            return
        with self._lock:
            prof["state"] = "capturing"
        obs.event("profile_start", dir=prof["dir"],
                  steps=prof["steps_requested"])

    def _profile_tick_end(self) -> None:
        prof = self._profile
        if prof is None or prof["state"] != "capturing":
            return
        with self._lock:
            prof["remaining"] -= 1
            finished = prof["remaining"] <= 0
        if finished:
            self._finish_profile()

    def _finish_profile(self, aborted: bool = False) -> None:
        prof = self._profile
        if prof is None:
            return
        try:
            import jax

            jax.profiler.stop_trace()
            state = "aborted" if aborted else "done"
            err = None
        except Exception as e:
            state = "error"
            err = f"{type(e).__name__}: {str(e)[:200]}"
        with self._lock:
            prof["state"] = state
            if err is not None:
                prof["error"] = err
            prof["steps_captured"] = \
                prof["steps_requested"] - max(0, prof["remaining"])
            self.last_profile = {k: v for k, v in prof.items()
                                 if k != "remaining"}
            self._profile = None
        obs.event("profile_done", dir=prof["dir"], state=state,
                  steps_captured=prof["steps_captured"])

    # ------------------------------------------------------------------
    # resident partitions: the update/epoch/compact verbs (ISSUE 15)
    # ------------------------------------------------------------------
    def _resident_path(self, job_id: str) -> Optional[str]:
        if self.ckpt_dir is None:
            return None
        return os.path.join(self.ckpt_dir, f"{job_id}.resident.npz")

    def update(self, job_id: str, adds=None, dels=None,
               epoch=None, score: bool = False, compact: str = "auto",
               log: Optional[str] = None,
               timeout_s: float = 600.0) -> dict:
        """Apply one delta epoch (or a daemon-side delta log's pending
        epochs) to a resident partition. Handler-thread API: the fold
        itself runs on the dispatch thread (one device chain)."""
        return self._submit_item(
            {"kind": "update", "job_id": job_id, "adds": adds,
             "dels": dels, "epoch": epoch, "score": bool(score),
             "compact": str(compact), "log": log}, timeout_s)

    def epoch_info(self, job_id: str,
                   timeout_s: float = 600.0) -> dict:
        return self._submit_item(
            {"kind": "epoch", "job_id": job_id}, timeout_s)

    def compact_resident(self, job_id: str, mode: str = "auto",
                         score: bool = False,
                         timeout_s: float = 600.0) -> dict:
        return self._submit_item(
            {"kind": "compact", "job_id": job_id, "mode": str(mode),
             "score": bool(score)}, timeout_s)

    def _submit_item(self, item: dict, timeout_s: float) -> dict:
        item["evt"] = threading.Event()
        # fairness bookkeeping (ISSUE 17): every queued item carries
        # its tenant and payload size so _service_updates can enforce
        # per-tenant byte budgets without re-locking the job table
        nb = 0
        for k in ("adds", "dels"):
            if item.get(k) is not None:
                nb += 16 * len(item[k])
        item["bytes"] = nb
        with self._lock:
            if self._stop or self._suspending:
                raise protocol.ProtocolError("daemon is shutting down")
            job = self._jobs.get(item["job_id"])
            if job is None:
                raise protocol.ProtocolError(
                    f"unknown job {item['job_id']!r}")
            item["tenant"] = job.spec.tenant
            self._updates.append(item)
            self._cond.notify_all()
        if not item["evt"].wait(timeout=timeout_s):
            with self._lock:
                try:
                    # still queued: dequeue it so the abandoned
                    # request cannot fire AFTER the client was told
                    # it timed out (a blind retry of an un-epoched
                    # update would then double-fold)
                    self._updates.remove(item)
                    dequeued = True
                except ValueError:
                    dequeued = False  # already executing
                item["abandoned"] = True
            if dequeued:
                raise protocol.ProtocolError(
                    f"{item['kind']} timed out after {timeout_s}s "
                    f"waiting for the dispatch thread; the request "
                    f"was dequeued — safe to retry")
            raise protocol.ProtocolError(
                f"{item['kind']} timed out after {timeout_s}s "
                f"mid-execution; it may still apply — query `epoch` "
                f"before retrying an un-epoched update")
        if item.get("error") is not None:
            raise protocol.ProtocolError(item["error"])
        return item["result"]

    def _service_updates(self) -> None:
        """Dispatch-thread drain of the resident-partition work queue
        (between job-step cycles, same thread as every device fold).

        Fairness (ISSUE 17): ``SHEEP_UPDATE_BYTES_PER_CYCLE`` caps the
        delta bytes each tenant may fold per drain cycle. A tenant
        streaming huge epochs exhausts its budget and its remaining
        items are DEFERRED to the next cycle (counted in
        ``sheepd_update_throttled_total``), letting other tenants' —
        and the build queue's — work interleave. Budgets reset every
        cycle, so deferred items always make progress; unset or 0
        means unlimited (the pre-ISSUE-17 FIFO drain)."""
        try:
            budget = int(os.environ.get(
                "SHEEP_UPDATE_BYTES_PER_CYCLE", "0") or "0")
        except ValueError:
            budget = 0
        spent: dict = {}
        while True:
            with self._lock:
                item = None
                for i, it in enumerate(self._updates):
                    t = it.get("tenant", "default")
                    if budget <= 0 or spent.get(t, 0) < budget \
                            or it.get("abandoned"):
                        item = it
                        del self._updates[i]
                        break
                if item is None:
                    # every queued tenant exhausted its cycle budget:
                    # leave the rest queued, one throttle tick per
                    # deferred item, pick them up next cycle
                    for it in self._updates:
                        self._m_update_throttled.inc(
                            tenant=it.get("tenant", "default"))
                    return
                if item.get("abandoned"):
                    continue  # its waiter already gave up
                spent[item.get("tenant", "default")] = \
                    spent.get(item.get("tenant", "default"), 0) \
                    + int(item.get("bytes", 0))
            try:
                with self.flight.job_context(item["job_id"]):
                    item["result"] = self._do_item(item)
                item["error"] = None
            except protocol.ProtocolError as e:
                item["error"] = str(e)
            except Exception as e:  # noqa: BLE001 — answered, not fatal
                item["error"] = (f"internal: {type(e).__name__}: "
                                 f"{str(e)[:300]}")
            finally:
                item["evt"].set()

    def _ensure_resident_state(self, job: Job):
        """The job's live resident state, lazily reloaded from its
        snapshot after a restart (the snapshot is written BEFORE each
        journaled delta_epoch, so its epoch >= the journal floor —
        'resumes at its last applied epoch'). Dispatch thread only."""
        from sheep_tpu import incremental

        if not job.spec.resident:
            raise protocol.ProtocolError(
                f"job {job.id} was not submitted resident")
        if job.resident_released:
            raise protocol.ProtocolError(
                f"job {job.id}'s resident partition was released")
        if job.state != DONE:
            raise protocol.ProtocolError(
                f"job {job.id} is {job.state}; a resident partition "
                f"exists only after the build is done")
        if job.resident_state is not None:
            return job.resident_state
        path = self._resident_path(job.id)
        if path is None or not os.path.exists(path):
            raise protocol.ProtocolError(
                f"job {job.id} has no resident state on disk "
                f"(non-durable daemon restarted, or state lost); "
                f"rebuild with a fresh resident submit")
        job.resident_state = incremental.load_state(path)
        if job.resident_state.epoch < job.journaled_epoch:
            # the journal promised an epoch the snapshot predates —
            # never silently serve the older state
            raise protocol.ProtocolError(
                f"resident snapshot of {job.id} is at epoch "
                f"{job.resident_state.epoch} but the journal floors "
                f"{job.journaled_epoch}; state dir damaged")
        obs.event("resident_resumed", job=job.id,
                  epoch=int(job.resident_state.epoch))
        return job.resident_state

    def _update_backend_for(self, job: Job):
        if job._upd_backend is None:
            from sheep_tpu.backends.base import get_backend

            spec = job.spec
            name = getattr(spec, "update_backend", "tpu") or "tpu"
            kw = {"chunk_edges": spec.chunk_edges, "alpha": spec.alpha}
            if name.startswith("tpu"):
                # the single-process backends take no segment knob;
                # every tpu* fold pipeline does
                kw["segment_rounds"] = spec.segment_rounds
            job._upd_backend = get_backend(name, **kw)
        return job._upd_backend

    def _persist_resident(self, job: Job,
                          journal_epoch: bool = True) -> None:
        """Snapshot the resident state, then (optionally) journal the
        applied epoch — strictly in that order, so a replayed journal
        never names an epoch the snapshot lacks. Dispatch thread only
        (the sole state mutator), and the O(V) array write + fsync
        deliberately runs OUTSIDE the scheduler lock: a multi-second
        snapshot of a big resident table must not stall every
        ping/status/submit handler. Only the journal append and the
        epoch-floor bookkeeping take the lock."""
        from sheep_tpu import incremental

        with self._lock:
            if job.resident_released:
                return  # cancel raced us before the write: nothing
            st = job.resident_state
            path = self._resident_path(job.id)
        if st is None or path is None:
            return
        incremental.save_state(st, path)
        with self._lock:
            if job.resident_released:
                # cancel released the residency DURING the write: the
                # unlink it did must win — remove the snapshot we just
                # resurrected and journal nothing
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return
            if journal_epoch and self.journal is not None:
                self.journal.append(
                    {"rec": "delta_epoch", "job_id": job.id,
                     "epoch": int(st.epoch), "t": time.time()},
                    fsync=True)
            job.journaled_epoch = max(job.journaled_epoch,
                                      int(st.epoch))

    def _do_item(self, item: dict) -> dict:
        from sheep_tpu import incremental

        with self._lock:
            job = self._jobs.get(item["job_id"])
        if job is None:
            raise protocol.ProtocolError(
                f"unknown job {item['job_id']!r}")
        state = self._ensure_resident_state(job)
        tenant = job.spec.tenant
        if item["kind"] == "epoch":
            return {"job_id": job.id, "epoch": int(state.epoch),
                    "anchored_at_epoch": int(state.anchored_at_epoch),
                    "stale_deletes": int(state.stale_deletes),
                    "compactions": int(state.compactions),
                    "n_vertices": int(state.n),
                    "total_edges": int(state.total_edges)}
        backend = self._update_backend_for(job)
        if item["kind"] == "compact":
            t0 = time.perf_counter()
            old_base = None
            if item["mode"] == "rebase":
                mode, old_base = self._rebase_resident(state, job,
                                                       backend)
            else:
                mode = incremental.compact_state(backend, state,
                                                 mode=item["mode"])
            if mode != "noop":
                self._m_compactions.inc(tenant=tenant, mode=mode)
            out = {"job_id": job.id, "mode": mode,
                   "epoch": int(state.epoch),
                   "compactions": int(state.compactions),
                   "wall_s": round(time.perf_counter() - t0, 4)}
            if mode == "rebase":
                out["base"] = state.base_spec
            if item.get("score"):
                out["results"] = self._refresh_results(
                    backend, state, job)
            self._persist_resident(job)
            if old_base is not None:
                # drop the superseded rebase artifact only AFTER the
                # snapshot + journal referencing the new base are
                # durable — a crash in between leaves both bases on
                # disk, never neither
                try:
                    os.unlink(old_base)
                except OSError:
                    pass
            return out
        # ---- update -------------------------------------------------
        t0 = time.perf_counter()
        epochs = []
        if item.get("log"):
            from sheep_tpu.io.deltalog import DeltaLogReader

            reader = DeltaLogReader(item["log"])
            base = reader.header["base_spec"]
            if state.base_spec is not None \
                    and base != state.base_spec:
                raise protocol.ProtocolError(
                    f"delta log {item['log']!r} logs over {base!r}, "
                    f"not this partition's base "
                    f"{state.base_spec!r}")
            epochs = list(reader.epochs(start_epoch=state.epoch))
        else:
            epochs = [(item.get("epoch"), item.get("adds"),
                       item.get("dels"))]
        compactions0 = int(state.compactions)
        applied = 0
        for ep, adds, dels in epochs:
            before = int(state.epoch)
            backend.partition_update(
                state, adds=adds, deletes=dels, epoch=ep,
                score=False, compact=item.get("compact", "auto"))
            if int(state.epoch) != before:
                # count applied BATCHES, not the epoch-number delta:
                # explicit epochs may be sparse (1 then 5 is legal)
                applied += 1
        if applied > 0:
            self._m_updates.inc(applied, tenant=tenant)
            comp = int(state.compactions) - compactions0
            if comp:
                self._m_compactions.inc(comp, tenant=tenant,
                                        mode="auto")
            self._persist_resident(job)
        out = {"job_id": job.id, "epoch": int(state.epoch),
               "applied": applied > 0, "epochs_applied": applied,
               "stale_deletes": int(state.stale_deletes),
               "compactions": int(state.compactions)}
        if item.get("score"):
            ts = time.perf_counter()
            out["results"] = self._refresh_results(backend, state, job)
            self._m_update_score.observe(time.perf_counter() - ts,
                                         tenant=tenant)
        self._m_update_latency.observe(time.perf_counter() - t0,
                                       tenant=tenant)
        obs.event("job_update", job=job.id, tenant=tenant,
                  epoch=int(state.epoch), applied=applied)
        return out

    def _refresh_results(self, backend, state, job: Job) -> list:
        """Split + score the current resident table; the job's result
        rows update so wait/status serve the newest scores."""
        from sheep_tpu import incremental

        res = incremental.refresh(backend, state,
                                  comm_volume=job.spec.comm_volume)
        results = res if isinstance(res, list) else [res]
        with self._lock:
            job.results = results
        for r in results:
            self._m_quality_cut.observe(float(r.cut_ratio),
                                        tenant=job.spec.tenant)
            self._m_quality_balance.observe(float(r.balance),
                                            tenant=job.spec.tenant)
            obs.event("job_quality", job=job.id, k=int(r.k),
                      cut_ratio=round(float(r.cut_ratio), 6),
                      balance=round(float(r.balance), 4),
                      edge_cut=int(r.edge_cut))
        return [r.summary() for r in results]

    def _rebase_resident(self, state, job: Job, backend):
        """Compact mode ``rebase`` (ISSUE 17): rewrite the resident
        base + folded deltas into a fresh CSR artifact under the
        checkpoint dir, so the served partition's read path stops
        paying for history. Explicit opt-in only — ``auto`` never
        escalates to it. Returns ``("rebase", old_artifact_or_None)``;
        the caller unlinks the superseded artifact only after the new
        snapshot + journal record are durable."""
        from sheep_tpu import incremental

        if self.ckpt_dir is None:
            raise protocol.ProtocolError(
                "compact mode 'rebase' needs a durable daemon "
                "(--state-dir / --checkpoint-dir): the rewritten "
                "base is a disk artifact")
        old = state.base_spec
        base_out = os.path.join(
            self.ckpt_dir, f"{job.id}.base.e{int(state.epoch)}.csr")
        incremental.rebase_state(backend, state, base_out)
        owned = None
        if isinstance(old, str) and old != base_out \
                and os.path.isfile(old) \
                and os.path.dirname(os.path.abspath(old)) \
                == os.path.abspath(self.ckpt_dir):
            # only reap artifacts WE wrote (a prior rebase): a base
            # outside the ckpt dir is user input, never ours to delete
            owned = old
        return "rebase", owned

    # ------------------------------------------------------------------
    # the dispatch loop (one thread)
    # ------------------------------------------------------------------
    def run(self) -> None:
        """Round-robin dispatch until shutdown; see module docstring."""
        try:
            while True:
                to_close: list = []
                with self._lock:
                    self._expire_locked()
                    if self._stop:
                        for job in list(self._pending):
                            self._pending.remove(job)
                            self._finalize_locked(job, CANCELLED)
                        for job in list(self._active):
                            self._finalize_locked(job, CANCELLED)
                            to_close.append(job)
                if self._stop:
                    for job in to_close:
                        self._close_gen(job)
                    return
                if self._suspending:
                    # graceful drain: no admissions, checkpoint + park
                    # the active jobs, exit once everything is parked
                    if self._suspend_cycle():
                        return
                    continue
                with self._lock:
                    self._admit_locked()
                    if self._draining and not self._pending \
                            and not self._active:
                        return
                    idle = not self._active and not self._updates
                    capturing = self._profile is not None \
                        and self._profile["state"] == "capturing"
                    if idle and not capturing:
                        # bounded wait: queued-job deadlines tick
                        # while idle
                        self._cond.wait(timeout=0.1)
                    cycle = [] if idle else list(self._active)
                if idle:
                    if capturing:
                        # the job set drained mid-capture: there is no
                        # Kth step coming — stop the profiler now (an
                        # open capture grows host memory forever and
                        # blocks every re-arm)
                        self._finish_profile(aborted=True)
                    continue
                for job in cycle:
                    self._step(job)
                # resident-partition verbs drain between step cycles:
                # delta folds share the one dispatch chain (ISSUE 15)
                self._service_updates()
        finally:
            self._teardown_telemetry()

    def _teardown_telemetry(self) -> None:
        """Dispatch-loop exit sweep: stop a mid-flight profiler
        capture, dump the flight recorder (shutdown is a dump trigger
        — the daemon's last moments are forensics too), release the
        process-wide recorder slot."""
        prof = self._profile
        if prof is not None and prof.get("state") == "capturing":
            self._finish_profile(aborted=True)
        with self._lock:
            pending_items = list(self._updates)
            self._updates.clear()
        for item in pending_items:
            # answer every parked update verb: a handler thread must
            # never ride its full timeout because the loop exited
            item["error"] = "daemon is shutting down"
            item["evt"].set()
        self.flight.dump_all(reason="shutdown")
        if obs.get_flight() is self.flight:
            obs.uninstall_flight()
        with self._lock:
            if self.journal is not None:
                self.journal.close()

    def _expire_locked(self) -> None:
        # reentrant re-acquire (RLock): callers already hold the lock;
        # taking it here too keeps every mutation lexically guarded
        with self._lock:
            now = time.time()
            for job in [j for j in self._pending
                        if j.deadline_t is not None
                        and now >= j.deadline_t]:
                self._pending.remove(job)
                self._finalize_locked(job, DEADLINE_EXCEEDED)

    def _admit_locked(self) -> None:
        with self._lock:
            while self._pending:
                job = self._pending[0]
                if self.budget is not None:
                    # resident partitions count: their tables re-enter
                    # device memory on every update fold (ISSUE 15)
                    reserved = self._reserved_locked()
                    if (self._active or reserved) and \
                            reserved + (job.modeled_bytes or 0) \
                            > self.budget:
                        if not self._active \
                                and not job.stats.get(
                                    "blocked_by_resident"):
                            # nothing running will ever free these
                            # bytes — only a tenant releasing a
                            # resident partition can; say so ONCE so
                            # the wait is diagnosable, not silent
                            job.stats["blocked_by_resident"] = 1
                            obs.event("admission_blocked_by_resident",
                                      job=job.id,
                                      tenant=job.spec.tenant,
                                      reserved_bytes=int(reserved),
                                      budget_bytes=int(self.budget))
                        break  # fits the budget, not current headroom
                self._pending.popleft()
                self._start_locked(job)

    def _start_locked(self, job: Job) -> None:
        with self._lock:
            job.state = RUNNING
            job.start_t = time.time()
            job.jit_compiles = 0
            self._m_queue_wait.observe(job.start_t - job.submit_t,
                                       tenant=job.spec.tenant)
            job.span = obs.begin_detached(
                f"job:{job.id}", parent=self.root_span_id,
                remote_parent=({"trace": job.trace_id,
                                "span": job.trace_parent}
                               if job.trace_id else None),
                job=job.id, tenant=job.spec.tenant, input=job.spec.input,
                k=list(job.spec.ks))
            job.span_id = getattr(job.span, "id", None)
            cache = self._lease_cache_locked(job)
            if self.ckpt_dir is not None:
                # per-job recovery domain: job ids are stable across
                # restarts (the journal floors the id counter), so a
                # re-admitted job finds exactly its own prior state;
                # resume=True is a no-op on an empty domain
                from sheep_tpu.utils.checkpoint import Checkpointer

                job.ckpt = Checkpointer(
                    os.path.join(self.ckpt_dir, job.id),
                    every=self.ckpt_every)
            engine = JobEngine(job, cache=cache, checkpointer=job.ckpt,
                               resume=job.ckpt is not None)
            job.engine = engine
            job.gen = engine.steps()
            if self.journal is not None:
                # buffered, not fsync'd: losing this record merely
                # replays the job as queued (a clean re-start)
                self.journal.append({"rec": "state", "job_id": job.id,
                                     "state": RUNNING,
                                     "t": job.start_t})
            self._active.append(job)
            obs.event("job_admit", job=job.id, tenant=job.spec.tenant,
                      modeled_bytes=job.modeled_bytes,
                      active=len(self._active))
            self._cond.notify_all()

    def _step(self, job: Job) -> None:
        cut = None
        with self._lock:
            if job.state != RUNNING:
                return
            if job.cancel_requested:
                self._finalize_locked(job, CANCELLED)
                cut = job
            elif job.deadline_t is not None \
                    and time.time() >= job.deadline_t:
                self._finalize_locked(job, DEADLINE_EXCEEDED)
                cut = job
        if cut is not None:
            # the unwind (prefetch-worker joins) runs OUTSIDE the lock
            # so a slow close cannot stall ping/status/submit handlers
            self._close_gen(cut)
            return
        # the device work happens OUTSIDE the lock: submits/cancels/
        # waits from handler threads must never block on a fold. Steps
        # are serialized on this one thread, so the compile-cache
        # growth across ONE step belongs to exactly this job — the
        # exact per-job jit attribution under interleaving. The same
        # serialization makes the flight-recorder job context exact:
        # every event the engine/retry layer emits during THIS next()
        # lands in THIS job's ring.
        self._profile_tick_begin()
        jit0 = sum(compile_cache_sizes().values())
        t_step = time.perf_counter()
        try:
            try:
                with self.flight.job_context(job.id):
                    phase = next(job.gen)
            finally:
                grew = sum(compile_cache_sizes().values()) - jit0
                if grew and job.jit_compiles is not None:
                    job.jit_compiles += grew
                self._profile_tick_end()
            self._m_step_s.observe(time.perf_counter() - t_step,
                                   phase=str(phase))
            self._m_steps.inc(tenant=job.spec.tenant)
            with self._lock:
                job.steps += 1
                job.phase = str(phase)
            return
        except StopIteration:
            outcome, error = DONE, None
        except Exception as exc:  # noqa: BLE001 — job fault, not ours
            outcome = FAILED
            error = f"{type(exc).__name__}: {str(exc)[:300]}"
        with self._lock:
            self._finalize_locked(job, outcome, error)
        if outcome == DONE and job.resident_state is not None:
            # the adopted resident partition's initial snapshot —
            # outside the lock, on the dispatch thread (ISSUE 15)
            self._persist_resident(job, journal_epoch=False)
        if outcome == DONE:
            # fleet warm path (ISSUE 16): publish strictly AFTER the
            # fsync'd journal terminal, outside the lock, on the
            # dispatch thread — a kill -9 between the two resolves to
            # a rebuild on the next identical submit, never a torn or
            # unjournaled answer
            self._publish_result(job)
        if outcome == FAILED:
            # forensics: the job's last N buffered events (terminal
            # event included — job_done landed in the ring at
            # finalize), dumped into the trace sink OUTSIDE the lock:
            # a slow trace write must not wedge every handler thread
            self.flight.dump(job.id, reason="job_failed:"
                             f"{(error or '?')[:120]}")
        self._close_gen(job)

    def _publish_result(self, job: Job) -> None:
        """Persist a DONE job's results into the content-addressed
        store (ISSUE 16). Best-effort: a failed publish costs the next
        identical submit a rebuild, never an error."""
        store = self.result_store
        if store is None or job.spec.resident or not job.results \
                or not job.digest:
            return
        rows = []
        for r in job.results:
            row = r.summary()
            row["assignment"] = protocol.encode_assignment(r.assignment)
            rows.append(row)
        try:
            ok = store.put(job.digest, {
                "t": job.end_t or time.time(),
                "tenant": job.spec.tenant,
                "n_vertices": int(job.n_vertices), "results": rows})
        except (OSError, ValueError) as e:
            obs.event("result_cache_error", job=job.id,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            return
        delta = store.evictions - self._rc_evictions_seen
        if delta > 0:
            self._m_rc_evictions.inc(delta)
            self._rc_evictions_seen = store.evictions
        if ok:
            obs.event("result_cache_store", job=job.id,
                      digest=job.digest, bytes=store.bytes_used)

    def lookup_digest(self, digest) -> bool:
        """The ``lookup`` verb (ISSUE 16): does this replica's result
        store hold an entry for ``digest``? Advisory — a damaged entry
        reports a miss here (the submit path applies the full
        strict/quarantine contract when it actually serves)."""
        store = self.result_store
        if store is None or not isinstance(digest, str):
            return False
        try:
            return store.get(digest) is not None
        except ValueError:
            return False

    # terminal jobs retained for status/wait queries; beyond this the
    # oldest are evicted (with their result arrays) — a resident
    # daemon must not grow host memory monotonically with traffic
    MAX_TERMINAL_RETAINED = 512

    def _finalize_locked(self, job: Job, state: str,
                         error: Optional[str] = None) -> None:
        """Terminal transition: release the reservation + cache lease,
        end the job span, account, evict old terminal jobs, notify.
        Does NOT close the step generator — the dispatch thread does
        that OUTSIDE the lock (:meth:`_close_gen`): the unwind joins
        prefetch workers and must not stall every handler thread."""
        with self._lock:
            if job.state in TERMINAL_STATES:
                return
            job.state = state
            job.error = error
            job.end_t = time.time()
            try:
                self._active.remove(job)
            except ValueError:
                pass
            self._release_cache_locked(job)
            if state == DONE:
                self._write_output(job)
            if state == DONE and job.spec.resident \
                    and job.incremental_state is not None:
                # adopt the engine's incremental state as the resident
                # partition (ISSUE 15); the initial snapshot is
                # written by _step AFTER this lock releases (an O(V)
                # disk write must not stall the handler threads) —
                # until it lands, a crash replays the job as DONE
                # with no resident state, the documented non-durable
                # degradation
                job.resident_state = job.incremental_state
                job.incremental_state = None
            self.totals[state] = self.totals.get(state, 0) + 1
            self._m_terminal.inc(tenant=job.spec.tenant, state=state)
            if state == DONE:
                # the SLO series: queued->done, queue wait included —
                # the client asked for a result at submit, not at start
                self._m_latency.observe(job.end_t - job.submit_t,
                                        tenant=job.spec.tenant)
                for r in job.results or []:
                    # the quality plane (ISSUE 13): every result k is
                    # one observation in the tenant's cut/balance
                    # distributions
                    self._m_quality_cut.observe(
                        float(r.cut_ratio), tenant=job.spec.tenant)
                    self._m_quality_balance.observe(
                        float(r.balance), tenant=job.spec.tenant)
            retries = job.stats.get("dispatch_retries")
            if isinstance(retries, (int, float)) and retries:
                self._m_retries.inc(int(retries), tenant=job.spec.tenant)
            if self.journal is not None:
                results = None
                if state == DONE and job.results:
                    results = [r.summary() for r in job.results]
                self.journal.append(
                    {"rec": "terminal", "job_id": job.id,
                     "state": state, "t": job.end_t,
                     **({"error": error} if error else {}),
                     **({"results": results} if results else {})},
                    fsync=True)
            if job.ckpt is not None:
                # terminal jobs leave no checkpoint residue: the
                # per-job domain dies with the job (a replayed
                # terminal never resumes)
                try:
                    job.ckpt.clear(force=True)
                    os.rmdir(job.ckpt.dir)
                except OSError:
                    pass
                job.ckpt = None
            job.engine = None
            if job.span is not None:
                cost = {k: job.stats[k]
                        for k in ("device_rounds", "host_syncs",
                                  "batch_execs", "dispatch_retries")
                        if k in job.stats}
                job.span.end(state=state,
                             jit_compiles=job.jit_compiles, **cost)
            obs.event("job_done", job=job.id, tenant=job.spec.tenant,
                      state=state, error=error,
                      jit_compiles=job.jit_compiles,
                      steps=job.steps)
            if state == DONE:
                # healthy jobs leave no ring behind: failed/cancelled
                # rings are worth retaining for the shutdown sweep, a
                # done job's is just noise
                self.flight.forget(job.id)
            terminal = [jid for jid, j in self._jobs.items()
                        if j.state in TERMINAL_STATES
                        and not self._is_resident(j)]
            for jid in terminal[:max(0, len(terminal)
                                     - self.MAX_TERMINAL_RETAINED)]:
                del self._jobs[jid]
            self._cond.notify_all()

    def _close_gen(self, job: Job) -> None:
        """Unwind a finalized job's step generator (engine finallys:
        chunk/group iterators close, prefetch workers cancel + join,
        phase spans end). Dispatch-thread only — generators are never
        touched from handler threads — and deliberately outside the
        scheduler lock (a stuck reader's bounded join must not freeze
        the API)."""
        gen, job.gen = job.gen, None
        if gen is None:
            return
        try:
            gen.close()
        except Exception as e:  # unwind failure: on record, not fatal
            import sys

            obs.event("job_unwind_error", job=job.id,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            print(f"sheepd: unwind of {job.id} raised "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)

    def _write_output(self, job: Job) -> None:
        if not job.spec.output or not job.results:
            return
        from sheep_tpu.io.formats import write_partition

        try:
            for r in job.results:
                path = job.spec.output
                if len(job.results) > 1:
                    root, ext = os.path.splitext(path)
                    path = f"{root}.k{r.k}{ext}"
                write_partition(path, r.assignment)
        except Exception as e:
            job.error = (f"partition finished but output write failed: "
                         f"{type(e).__name__}: {str(e)[:200]}")

    # ------------------------------------------------------------------
    # shared device chunk cache (one filler + any readers per input)
    # ------------------------------------------------------------------
    def _lease_cache_locked(self, job: Job):
        """The daemon-held device chunk cache for this job's input, or
        None. The backends' prefix-fill invariant assumes a single
        FILLER, so the first job on an input leases the cache itself
        (it appends); concurrent jobs on the same input get a
        read-only view (ISSUE 16) that serves the cached prefix and
        streams the rest without ever appending — interleaved jobs
        share the resident chunks instead of the second one
        re-streaming everything. All access stays on the one dispatch
        thread, so reads and fills never race. Budget comes from the
        backends' own rule (0 on cpu-jax, where "device" memory is
        the host's)."""
        from sheep_tpu.backends.tpu_backend import (_ChunkCache,
                                                    _ChunkCacheReader,
                                                    _chunk_cache_budget)

        if job.spilled:
            # spilled-mode admission priced this job at the cache-less
            # floor; leasing resident chunks would put back exactly the
            # bytes the admission model zeroed out
            return None
        with self._lock:
            key = (job.spec.input, job.spec.chunk_edges,
                   job.n_vertices)
            entry = self._caches.get(key)
            if entry is None:
                budget = _chunk_cache_budget(job.n_vertices,
                                             job.spec.chunk_edges)
                if budget <= 0:
                    return None
                entry = {"cache": _ChunkCache(budget),
                         "filler": None, "readers": set()}
                self._caches[key] = entry
                # bound resident inputs — but never evict a HELD
                # entry: its chunks are pinned by the running engines
                # anyway, and dropping the entry would orphan the
                # lease and invite a duplicate cache for the same key
                evictable = [k for k, e in self._caches.items()
                             if e["filler"] is None
                             and not e["readers"] and k != key]
                while len(self._caches) > 4 and evictable:
                    del self._caches[evictable.pop(0)]
            if entry["filler"] is None:
                entry["filler"] = job.id
                return entry["cache"]
            entry["readers"].add(job.id)
            return _ChunkCacheReader(entry["cache"])

    def _release_cache_locked(self, job: Job) -> None:
        with self._lock:
            for key, entry in list(self._caches.items()):
                if entry["filler"] == job.id:
                    entry["filler"] = None
                    if job.cache_shed:
                        # the engine detached under memory pressure:
                        # drop the entry so the HBM dies with the
                        # engines' references and the next job on this
                        # input starts a fresh, freshly-budgeted cache
                        # (live readers keep serving their view — it
                        # references the cache object directly)
                        del self._caches[key]
                else:
                    entry["readers"].discard(job.id)
