"""Multi-tenant job queue + admission + the interleaving dispatch loop.

The scheduler owns every job from submit to terminal state:

**Admission (membudget-aware).** Each job's device footprint is
modeled up front with the same HBM model the backends auto-size
against (``utils/membudget.build_phase_bytes`` at the job's resolved
dispatch batch). Against the daemon's budget (``SHEEP_CACHE_BYTES``
override, else 90% of reported HBM, else unlimited on cpu-jax):

- a job that exceeds the WHOLE budget is first shed down the same
  degradation schedule an OOM would force (``membudget
  .degraded_dispatch`` — halve the batch while the model says that
  frees the most), and REJECTED only if it still cannot fit at the
  fully degraded shape;
- a job that fits the budget but not the current free headroom stays
  QUEUED until earlier jobs release their reservation;
- admitted jobs reserve their modeled bytes until terminal.

**Interleave.** Admitted jobs step round-robin on one thread: each
step is one staged group of device work
(:class:`~sheep_tpu.server.engine.JobEngine`), so segments from
different jobs alternate on one dispatch chain, each folding into its
own carried table (order-independence of each job's fixpoint in its
own constraint multiset makes this sound — and
tests/test_server.py pins interleaved == solo bit-equality).

**Warm programs.** The hot jitted entry points are module-level jit
caches; the scheduler snapshots their compile-cache sizes around every
job, so a served response can PROVE warm reuse (``jit_compiles == 0``
for a repeat shape) — the 8-13 s cold warm-up the daemon exists to
amortize (BENCH_r03-r05).

**Deadlines / cancellation.** Both are scheduler-side cuts between
steps: the job's step generator is closed (unwinding through the
engine's ``finally`` blocks — prefetch workers cancel via
``Prefetcher.close``, phase spans end) and only that job changes
state; the dispatch chain and every other job's table are untouched.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from sheep_tpu import obs
from sheep_tpu.server import protocol
from sheep_tpu.server.engine import JobEngine
from sheep_tpu.server.protocol import (CANCELLED, DEADLINE_EXCEEDED, DONE,
                                       FAILED, QUEUED, REJECTED, RUNNING,
                                       TERMINAL_STATES, JobSpec)


def _hot_programs():
    """The jitted entry points whose per-process compile caches ARE the
    daemon's warm state (one cache entry per distinct shape/static
    combination)."""
    from sheep_tpu.ops import degrees as degrees_ops
    from sheep_tpu.ops import elim as elim_ops
    from sheep_tpu.ops import order as order_ops
    from sheep_tpu.ops import score as score_ops

    return {
        "fold_segments_batch_pos": elim_ops.fold_segments_batch_pos,
        "fold_segments_batch_pos_donated":
            elim_ops.fold_segments_batch_pos_donated,
        "orient_chunks_batch_pos": elim_ops.orient_chunks_batch_pos,
        "degree_chunk": degrees_ops.degree_chunk,
        "elimination_order": order_ops.elimination_order,
        "score_chunk": score_ops.score_chunk,
    }


def compile_cache_sizes() -> dict:
    """{program: compiled-variant count} for the hot programs — the
    warm-reuse evidence (a repeat shape adds zero everywhere)."""
    out = {}
    for name, fn in _hot_programs().items():
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = -1  # jit internals changed; counter degraded
    return out


def resolve_budget_bytes(budget_bytes: Optional[int] = None):
    """The daemon's admission budget: an explicit flag wins, then the
    ``SHEEP_CACHE_BYTES`` override (the documented HBM-budget knob),
    then 90% of the accelerator's reported HBM; None = unlimited
    (cpu-jax, where "device" memory is host RAM and the model would
    gate nothing real)."""
    if budget_bytes is not None:
        return int(budget_bytes) if budget_bytes > 0 else None
    env = os.environ.get("SHEEP_CACHE_BYTES")
    if env is not None:
        try:
            val = int(env)
        except ValueError:
            val = 0
        if val > 0:
            return val
        # SHEEP_CACHE_BYTES=0 means "spend nothing on the chunk cache"
        # everywhere else (tpu_backend._chunk_cache_budget) — for
        # admission it must NOT mean "unlimited"; fall through to the
        # platform default instead
    import jax

    if jax.default_backend() == "cpu":
        return None
    from sheep_tpu.backends.tpu_backend import _device_hbm_bytes

    hbm = _device_hbm_bytes(purpose="the admission budget")
    return int(0.9 * hbm) if hbm > 0 else None


class Job:
    """One submitted job: spec + lifecycle + results. State transitions
    happen only under the scheduler's lock."""

    def __init__(self, job_id: str, spec: JobSpec, n_vertices: int,
                 modeled_bytes: Optional[int]):
        self.id = job_id
        self.spec = spec
        self.state = QUEUED
        self.error: Optional[str] = None
        self.submit_t = time.time()
        self.start_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.deadline_t = None if spec.deadline_s is None \
            else self.submit_t + spec.deadline_s
        self.n_vertices = n_vertices
        self.modeled_bytes = modeled_bytes
        self.stats: dict = {}
        self.results: Optional[list] = None
        self.gen = None           # the engine step generator, once running
        self.span = None          # detached obs span for the job tree
        self.span_id = None
        self.cancel_requested = False
        self.steps = 0
        # per-step compile-cache delta sum (None until started): the
        # dispatch thread serializes steps, so attributing each step's
        # global cache growth to the job that ran it is EXACT even
        # under interleaving — a finalize-time delta would blame one
        # job for every concurrent job's compiles
        self.jit_compiles: Optional[int] = None
        # the engine shed the shared chunk cache under memory pressure;
        # the scheduler drops the cache entry at finalize so the HBM is
        # released and future jobs start a fresh cache
        self.cache_shed = False

    def descriptor(self, with_results: bool = False) -> dict:
        d = {"job_id": self.id, "tenant": self.spec.tenant,
             "input": self.spec.input, "k": list(self.spec.ks),
             "state": self.state, "submit_t": round(self.submit_t, 3),
             "n_vertices": int(self.n_vertices),
             "modeled_bytes": self.modeled_bytes, "steps": self.steps}
        if self.error is not None:
            d["error"] = self.error
        if self.deadline_t is not None:
            d["deadline_t"] = round(self.deadline_t, 3)
        if self.start_t is not None:
            d["start_t"] = round(self.start_t, 3)
        if self.end_t is not None:
            d["end_t"] = round(self.end_t, 3)
            base = self.start_t if self.start_t is not None \
                else self.submit_t
            d["wall_s"] = round(self.end_t - base, 4)
        if self.jit_compiles is not None:
            d["jit_compiles"] = self.jit_compiles
        if self.state == DONE and self.results is not None:
            d["results"] = []
            for r in self.results:
                row = r.summary()
                if with_results and self.spec.return_assignment:
                    row["assignment"] = protocol.encode_assignment(
                        r.assignment)
                d["results"].append(row)
        return d


class Scheduler:
    """See module docstring. Thread model: any number of submitter
    threads (the daemon's connection handlers) call submit/cancel/wait;
    ONE dispatch thread calls :meth:`run`. All shared state is guarded
    by ``self._lock`` (the condition's lock)."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 root_span_id=None):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.budget = resolve_budget_bytes(budget_bytes)
        self.root_span_id = root_span_id
        self._jobs: "OrderedDict[str, Job]" = OrderedDict()
        self._pending: deque = deque()
        self._active: deque = deque()   # admitted; round-robin order
        self._ids = itertools.count(1)
        self._stop = False
        self._draining = False
        self._caches: "OrderedDict[tuple, dict]" = OrderedDict()
        self.totals = {"submitted": 0, "done": 0, "failed": 0,
                       "cancelled": 0, "rejected": 0,
                       "deadline_exceeded": 0}
        self.started_t = time.time()

    # ------------------------------------------------------------------
    # submit-side API (connection handler threads)
    # ------------------------------------------------------------------
    def submit(self, spec: JobSpec) -> Job:
        """Validate + model + enqueue. Raises ProtocolError on inputs
        that cannot be opened (answered ok=false; no job is created) —
        admission-budget verdicts come back as a REJECTED job instead,
        so they are queryable like any other terminal state."""
        n = self._probe_num_vertices(spec)
        modeled, batch, rejected_why = self._model(spec, n)
        with self._lock:
            if self._stop or self._draining:
                raise protocol.ProtocolError("daemon is shutting down")
            job = Job(f"j{next(self._ids)}", spec, n, modeled)
            # the admission pre-shed: run at the degraded batch that
            # fits (the same knob an OOM would halve mid-run)
            if batch is not None and batch != spec.dispatch_batch:
                job.spec.dispatch_batch = batch
                job.stats["admission_dispatch_batch"] = batch
            self._jobs[job.id] = job
            self.totals["submitted"] += 1
            if rejected_why is not None:
                job.state = REJECTED
                job.error = rejected_why
                job.end_t = time.time()
                self.totals["rejected"] += 1
            else:
                self._pending.append(job)
            obs.event("job_submit", job=job.id, tenant=spec.tenant,
                      input=spec.input, k=list(spec.ks), state=job.state,
                      modeled_bytes=modeled)
            self._cond.notify_all()
            return job

    def _probe_num_vertices(self, spec: JobSpec) -> int:
        from sheep_tpu.io.edgestream import open_input

        try:
            with open_input(spec.input,
                            n_vertices=spec.num_vertices) as es:
                return int(es.num_vertices)
        except Exception as e:
            raise protocol.ProtocolError(
                f"cannot open job input {spec.input!r}: "
                f"{type(e).__name__}: {str(e)[:200]}") from None

    def _model(self, spec: JobSpec, n: int):
        """(modeled_bytes, pre-shed dispatch_batch or None, reject
        reason or None) for admission. Models at the REQUESTED chunk
        size (clamping only shrinks it — conservative)."""
        from sheep_tpu.backends.tpu_backend import resolve_dispatch_batch
        from sheep_tpu.utils import membudget

        cs = spec.chunk_edges
        batch = resolve_dispatch_batch(spec.dispatch_batch, n, cs)
        if self.budget is None:
            return None, None, None

        def total(b):
            return membudget.build_phase_bytes(
                n, cs, dispatch_batch=b)["total_bytes"]

        m = total(batch)
        shed = None
        while m > self.budget:
            nxt = membudget.degraded_dispatch(n, cs, batch, 1)
            if nxt is None:
                return m, None, (
                    f"modeled device footprint {m:,} bytes exceeds the "
                    f"admission budget {self.budget:,} even at "
                    f"dispatch_batch=1 (V={n:,}, chunk_edges={cs:,}); "
                    f"shrink the graph/chunk or raise the budget")
            batch = nxt[0]
            shed = batch
            m = total(batch)
        return m, shed, None

    def get(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.values())

    def cancel(self, job_id: str) -> Optional[str]:
        """Request cancellation; returns the job's (possibly already
        terminal) state, or None for an unknown id. A queued job is
        finalized immediately — cancellation FREES THE QUEUE without
        waiting for a dispatch cycle. A RUNNING job's cancel is
        asynchronous (the returned state is still ``running``): the
        dispatch loop finalizes it before its next step — observe the
        terminal state with :meth:`wait`."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job.state in TERMINAL_STATES:
                return job.state
            if job.state == QUEUED:
                try:
                    self._pending.remove(job)
                except ValueError:
                    pass
                self._finalize_locked(job, CANCELLED)
            else:
                job.cancel_requested = True
                self._cond.notify_all()
            return job.state

    def wait(self, job_id: str, timeout_s: Optional[float] = None):
        """Block until the job is terminal (or timeout); returns the
        Job, or None for an unknown id."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._lock:
            while True:
                job = self._jobs.get(job_id)
                if job is None or job.state in TERMINAL_STATES:
                    return job
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return job
                self._cond.wait(timeout=0.1 if remaining is None
                                else min(0.1, remaining))

    def stats(self) -> dict:
        with self._lock:
            by_state: dict = {}
            for job in self._jobs.values():
                by_state[job.state] = by_state.get(job.state, 0) + 1
            reserved = sum(j.modeled_bytes or 0 for j in self._active)
            return {
                "uptime_s": round(time.time() - self.started_t, 1),
                "budget_bytes": self.budget,
                "reserved_bytes": reserved,
                "jobs": dict(self.totals),
                "jobs_by_state": by_state,
                "queued": len(self._pending),
                "active": len(self._active),
                "compile_cache": compile_cache_sizes(),
                "chunk_caches": len(self._caches),
            }

    def shutdown(self, drain: bool = False) -> None:
        """Stop the dispatch loop. ``drain`` finishes the jobs already
        accepted first; otherwise every non-terminal job is cancelled
        on the next cycle (their spans close — a clean shutdown leaves
        ZERO unclosed spans)."""
        with self._lock:
            if drain:
                self._draining = True
            else:
                self._stop = True
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # the dispatch loop (one thread)
    # ------------------------------------------------------------------
    def run(self) -> None:
        """Round-robin dispatch until shutdown; see module docstring."""
        while True:
            to_close: list = []
            with self._lock:
                self._expire_locked()
                if self._stop:
                    for job in list(self._pending):
                        self._pending.remove(job)
                        self._finalize_locked(job, CANCELLED)
                    for job in list(self._active):
                        self._finalize_locked(job, CANCELLED)
                        to_close.append(job)
            if self._stop:
                for job in to_close:
                    self._close_gen(job)
                return
            with self._lock:
                self._admit_locked()
                if self._draining and not self._pending \
                        and not self._active:
                    return
                if not self._active:
                    # bounded wait: queued-job deadlines tick while idle
                    self._cond.wait(timeout=0.1)
                    continue
                cycle = list(self._active)
            for job in cycle:
                self._step(job)

    def _expire_locked(self) -> None:
        # reentrant re-acquire (RLock): callers already hold the lock;
        # taking it here too keeps every mutation lexically guarded
        with self._lock:
            now = time.time()
            for job in [j for j in self._pending
                        if j.deadline_t is not None
                        and now >= j.deadline_t]:
                self._pending.remove(job)
                self._finalize_locked(job, DEADLINE_EXCEEDED)

    def _admit_locked(self) -> None:
        with self._lock:
            while self._pending:
                job = self._pending[0]
                if self.budget is not None:
                    reserved = sum(j.modeled_bytes or 0
                                   for j in self._active)
                    if self._active and \
                            reserved + (job.modeled_bytes or 0) \
                            > self.budget:
                        break  # fits the budget, not current headroom
                self._pending.popleft()
                self._start_locked(job)

    def _start_locked(self, job: Job) -> None:
        with self._lock:
            job.state = RUNNING
            job.start_t = time.time()
            job.jit_compiles = 0
            job.span = obs.begin_detached(
                f"job:{job.id}", parent=self.root_span_id, job=job.id,
                tenant=job.spec.tenant, input=job.spec.input,
                k=list(job.spec.ks))
            job.span_id = getattr(job.span, "id", None)
            cache = self._lease_cache_locked(job)
            job.gen = JobEngine(job, cache=cache).steps()
            self._active.append(job)
            obs.event("job_admit", job=job.id, tenant=job.spec.tenant,
                      modeled_bytes=job.modeled_bytes,
                      active=len(self._active))
            self._cond.notify_all()

    def _step(self, job: Job) -> None:
        cut = None
        with self._lock:
            if job.state != RUNNING:
                return
            if job.cancel_requested:
                self._finalize_locked(job, CANCELLED)
                cut = job
            elif job.deadline_t is not None \
                    and time.time() >= job.deadline_t:
                self._finalize_locked(job, DEADLINE_EXCEEDED)
                cut = job
        if cut is not None:
            # the unwind (prefetch-worker joins) runs OUTSIDE the lock
            # so a slow close cannot stall ping/status/submit handlers
            self._close_gen(cut)
            return
        # the device work happens OUTSIDE the lock: submits/cancels/
        # waits from handler threads must never block on a fold. Steps
        # are serialized on this one thread, so the compile-cache
        # growth across ONE step belongs to exactly this job — the
        # exact per-job jit attribution under interleaving.
        jit0 = sum(compile_cache_sizes().values())
        try:
            try:
                next(job.gen)
            finally:
                grew = sum(compile_cache_sizes().values()) - jit0
                if grew and job.jit_compiles is not None:
                    job.jit_compiles += grew
            with self._lock:
                job.steps += 1
            return
        except StopIteration:
            outcome, error = DONE, None
        except Exception as exc:  # noqa: BLE001 — job fault, not ours
            outcome = FAILED
            error = f"{type(exc).__name__}: {str(exc)[:300]}"
        with self._lock:
            self._finalize_locked(job, outcome, error)
        self._close_gen(job)

    # terminal jobs retained for status/wait queries; beyond this the
    # oldest are evicted (with their result arrays) — a resident
    # daemon must not grow host memory monotonically with traffic
    MAX_TERMINAL_RETAINED = 512

    def _finalize_locked(self, job: Job, state: str,
                         error: Optional[str] = None) -> None:
        """Terminal transition: release the reservation + cache lease,
        end the job span, account, evict old terminal jobs, notify.
        Does NOT close the step generator — the dispatch thread does
        that OUTSIDE the lock (:meth:`_close_gen`): the unwind joins
        prefetch workers and must not stall every handler thread."""
        with self._lock:
            if job.state in TERMINAL_STATES:
                return
            job.state = state
            job.error = error
            job.end_t = time.time()
            try:
                self._active.remove(job)
            except ValueError:
                pass
            self._release_cache_locked(job)
            if state == DONE:
                self._write_output(job)
            self.totals[state] = self.totals.get(state, 0) + 1
            if job.span is not None:
                cost = {k: job.stats[k]
                        for k in ("device_rounds", "host_syncs",
                                  "batch_execs", "dispatch_retries")
                        if k in job.stats}
                job.span.end(state=state,
                             jit_compiles=job.jit_compiles, **cost)
            obs.event("job_done", job=job.id, tenant=job.spec.tenant,
                      state=state, error=error,
                      jit_compiles=job.jit_compiles,
                      steps=job.steps)
            terminal = [jid for jid, j in self._jobs.items()
                        if j.state in TERMINAL_STATES]
            for jid in terminal[:max(0, len(terminal)
                                     - self.MAX_TERMINAL_RETAINED)]:
                del self._jobs[jid]
            self._cond.notify_all()

    def _close_gen(self, job: Job) -> None:
        """Unwind a finalized job's step generator (engine finallys:
        chunk/group iterators close, prefetch workers cancel + join,
        phase spans end). Dispatch-thread only — generators are never
        touched from handler threads — and deliberately outside the
        scheduler lock (a stuck reader's bounded join must not freeze
        the API)."""
        gen, job.gen = job.gen, None
        if gen is None:
            return
        try:
            gen.close()
        except Exception as e:  # unwind failure: on record, not fatal
            import sys

            obs.event("job_unwind_error", job=job.id,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            print(f"sheepd: unwind of {job.id} raised "
                  f"{type(e).__name__}: {str(e)[:200]}", file=sys.stderr)

    def _write_output(self, job: Job) -> None:
        if not job.spec.output or not job.results:
            return
        from sheep_tpu.io.formats import write_partition

        try:
            for r in job.results:
                path = job.spec.output
                if len(job.results) > 1:
                    root, ext = os.path.splitext(path)
                    path = f"{root}.k{r.k}{ext}"
                write_partition(path, r.assignment)
        except Exception as e:
            job.error = (f"partition finished but output write failed: "
                         f"{type(e).__name__}: {str(e)[:200]}")

    # ------------------------------------------------------------------
    # shared device chunk cache (one lease at a time per input)
    # ------------------------------------------------------------------
    def _lease_cache_locked(self, job: Job):
        """The daemon-held device chunk cache for this job's input, or
        None. One lease at a time per cache: the backends' prefix-fill
        invariant assumes a single filler, and the dispatch loop
        interleaves jobs on one thread, so a second simultaneous
        reader could double-append — the second job just streams.
        Budget comes from the backends' own rule (0 on cpu-jax, where
        "device" memory is the host's)."""
        from sheep_tpu.backends.tpu_backend import (_ChunkCache,
                                                    _chunk_cache_budget)

        with self._lock:
            key = (job.spec.input, job.spec.chunk_edges,
                   job.n_vertices)
            entry = self._caches.get(key)
            if entry is None:
                budget = _chunk_cache_budget(job.n_vertices,
                                             job.spec.chunk_edges)
                if budget <= 0:
                    return None
                entry = {"cache": _ChunkCache(budget),
                         "leased_by": None}
                self._caches[key] = entry
                # bound resident inputs — but never evict a LEASED
                # entry: its chunks are pinned by the running engine
                # anyway, and dropping the entry would orphan the
                # lease and invite a duplicate cache for the same key
                evictable = [k for k, e in self._caches.items()
                             if e["leased_by"] is None and k != key]
                while len(self._caches) > 4 and evictable:
                    del self._caches[evictable.pop(0)]
            if entry["leased_by"] is not None:
                return None
            entry["leased_by"] = job.id
            return entry["cache"]

    def _release_cache_locked(self, job: Job) -> None:
        with self._lock:
            for key, entry in list(self._caches.items()):
                if entry["leased_by"] == job.id:
                    entry["leased_by"] = None
                    if job.cache_shed:
                        # the engine detached under memory pressure:
                        # drop the entry so the HBM dies with the
                        # engine's references and the next job on this
                        # input starts a fresh, freshly-budgeted cache
                        del self._caches[key]
