"""sheepd wire protocol: newline-delimited JSON over a local socket.

One request per line, one response per line, strictly in order per
connection (a client may pipeline). Every response carries ``ok``:
``{"ok": true, ...}`` or ``{"ok": false, "error": "..."}`` — a
malformed request is answered, never dropped, and never kills the
connection, let alone the daemon.

Requests (``op`` selects):

    {"op": "ping"}
    {"op": "submit", "tenant": "alice", "job": {...JobSpec fields...},
     "reattach": false}
    {"op": "status", "job_id": "j3"}
    {"op": "wait",   "job_id": "j3", "timeout_s": 30}
    {"op": "cancel", "job_id": "j3"}
    {"op": "list"}
    {"op": "stats"}
    {"op": "metrics"}
    {"op": "profile", "dir": "/tmp/prof", "steps": 8}
    {"op": "update",  "job_id": "j3", "adds": {edges b64},
     "dels": {edges b64}, "epoch": 7, "score": false}
    {"op": "update",  "job_id": "j3", "log": "/path/g.dlog"}
    {"op": "update",  "job_id": "j3", "stream": "begin"}
    {"op": "update",  "txn": "u1", "stream": "chunk",
     "adds": {edges b64}, "dels": {edges b64}}
    {"op": "update",  "txn": "u1", "stream": "commit", "epoch": 7,
     "score": false, "compact": "auto"}
    {"op": "update",  "txn": "u1", "stream": "abort"}
    {"op": "epoch",   "job_id": "j3"}
    {"op": "compact", "job_id": "j3", "mode": "auto", "score": false}
    {"op": "shutdown", "drain": false, "suspend": false}
    {"op": "lookup", "digest": "<hex job digest>"}

Fleet verbs (ISSUE 16): ``lookup`` asks whether this replica's
content-addressed result store holds an entry for a job digest —
``{"ok": true, "hit": true|false}`` — without submitting anything. A
multi-endpoint client probes every replica with it first; a hit
short-circuits headroom routing entirely (the repeat submit answers
from the store with zero build steps and zero recompiles).

Incremental verbs (ISSUE 15): a job submitted with ``"resident":
true`` keeps its converged partition state resident after DONE —
admission keeps charging its modeled bytes to the membudget model
until the tenant releases it (``cancel`` on the DONE job). The tenant
then streams deltas at it: ``update`` folds an epoch of adds /
tombstones into the carried table in O(Δ) (inline base64 edge
payloads, bounded by the 1 MiB request line — ~20k edges per request
— or ``"log"`` naming a daemon-side delta log whose epochs past the
resident epoch all apply). Explicit ``epoch`` numbers make updates
IDEMPOTENT: an epoch at or below the resident epoch answers
``applied: false`` without refolding — the retry/replay contract.
``epoch`` queries the resident epoch/staleness; ``compact`` runs the
tombstone compaction (``mode`` auto/full/subtree, plus ``rebase`` on
a durable daemon: full compaction that REWRITES the base into a fresh
CSR artifact under the checkpoint dir, so the tombstone filter and
anchored history stay O(recent)). On a durable daemon every applied
epoch checkpoints the resident state and journals a ``delta_epoch``
record, so a SIGKILL'd daemon resumes the resident partition at its
last applied epoch bit-identically.

Chunked update framing (ISSUE 17): one epoch larger than the 1 MiB
request line streams through ``update`` sub-verbs selected by
``stream``. ``begin`` (carries ``job_id``) opens a transaction and
answers ``{"txn": "u1"}``; any number of ``chunk`` requests append
inline ``adds``/``dels`` payloads (each request still under the line
cap) to that txn; ``commit`` applies the accumulated delta as ONE
epoch through the normal update path (same answer shape, same
idempotent ``epoch`` semantics) and ``abort`` discards it.
Transactions are connection-scoped and staged host-side only: a
client that dies mid-stream (no commit) changes NOTHING — the
resident stays at its prior epoch and the whole txn is idempotently
retryable from ``begin``. Accumulation per txn is capped
(:data:`MAX_UPDATE_TXN_BYTES`) so a runaway stream cannot balloon the
daemon's host memory.

Durability verbs (ISSUE 14): ``submit`` with ``"reattach": true`` is
IDEMPOTENT — the daemon digests the spec (plus the input's content
identity) and, when a queued/running/done twin exists (journaled jobs
from before a restart included), answers that job's id with
``"reattached": true`` instead of building again; failed/cancelled/
rejected twins do not match (a fresh submit is the retry for those).
``shutdown`` with ``"suspend": true`` (durable daemons only;
``grace_s`` optional) is the graceful drain: stop admitting,
checkpoint running jobs at their next flush barrier, journal the
handoff, exit 0 — the restarted daemon resumes them. Job ids are
stable across restarts (the journal floors the id counter), so a
pre-restart ``job_id`` keeps working in status/wait/cancel; a
journal-replayed DONE job answers its journaled result summaries,
without assignment payloads (use ``output`` for those).

Trace context (ISSUE 18): every request may carry an optional
top-level ``trace`` field — a W3C-traceparent-shaped string
``"00-<32 hex trace id>-<16 hex parent span id>-01"`` minted by the
client once per LOGICAL request (a fleet submit keeps one trace id
across failover resubmits; waits/updates reuse the submit's). The
daemon threads it into the job's detached span and flight-recorder
ring, so one trace id stitches the client's route/failover spans and
every replica's job spans into one cross-process tree
(``tools/trace_report.py --stitch``). An all-zero parent span id
means "the client had no span of its own" (untraced client); the
trace id still correlates. The field is OPTIONAL and additive: old
clients never send it, old daemons ignore it — it is not a job field
and never affects the job digest (:func:`make_traceparent` /
:func:`parse_traceparent` are the codec).

Telemetry verbs (ISSUE 11): ``metrics`` answers ``{"ok": true,
"content_type": ..., "text": "<Prometheus exposition>"}`` — the same
document the daemon's optional HTTP ``GET /metrics`` listener
(``--metrics-port``) serves, with per-tenant request-latency
histograms, queue/reservation gauges and per-active-job progress.
``profile`` arms an on-demand ``jax.profiler`` capture of the next
``steps`` dispatch steps into ``dir`` (daemon-side path); the answer
confirms arming, capture progress is queryable under ``stats``'s
``profile`` field. Job descriptors carry live ``phase`` + ``steps``
progress fields while running (what ``sheep-submit --watch`` and
``sheeptop`` poll).

Job lifecycle (:data:`JOB_STATES`)::

    queued ----> running ----> done | failed | deadline_exceeded
       |            |
       |            +--------> cancelled
       +--> cancelled | rejected

``rejected`` is the admission scheduler's verdict for a job whose
modeled device footprint exceeds the daemon's whole budget even at the
fully degraded dispatch shape (membudget.build_phase_bytes at
dispatch_batch=1); ``queued`` jobs fit the budget but not the current
free headroom and run when earlier jobs release it.

Deadline semantics: ``deadline_s`` is measured from SUBMIT (queue wait
counts — the client asked for a result by then, not for a start). An
expired job reports ``deadline_exceeded`` whether it was still queued
or mid-build; expiry cancels only that job's step generator, never the
dispatch chain (other jobs' carried tables are untouched).

Assignments travel base64-packed (little-endian int32) only when the
submitter asked (``return_assignment``) — scores always travel.
"""

from __future__ import annotations

import base64
import json
import os
import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

# terminal states never transition again
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
DEADLINE_EXCEEDED = "deadline_exceeded"
REJECTED = "rejected"

JOB_STATES = (QUEUED, RUNNING, DONE, FAILED, CANCELLED,
              DEADLINE_EXCEEDED, REJECTED)
TERMINAL_STATES = (DONE, FAILED, CANCELLED, DEADLINE_EXCEEDED, REJECTED)

OPS = ("ping", "submit", "status", "wait", "cancel", "list", "stats",
       "metrics", "profile", "update", "epoch", "compact", "shutdown",
       "lookup")

MAX_REQUEST_BYTES = 1 << 20  # one request line; jobs are specs, not data

# chunked-update framing (ISSUE 17): the sub-verbs of {"op": "update",
# "stream": ...} and the per-transaction staging cap — 256 MiB of raw
# edge payload (16 bytes/edge, ~16M edges) per uncommitted txn
UPDATE_STREAM_VERBS = ("begin", "chunk", "commit", "abort")
MAX_UPDATE_TXN_BYTES = 256 << 20


class ProtocolError(ValueError):
    """Malformed request — answered with ok=false, never fatal."""


# -- trace context (ISSUE 18) ------------------------------------------
# W3C-traceparent-shaped: version "00", 32-hex trace id, 16-hex parent
# span id, flags "01" (sampled — sheep traces everything it traces).
_NO_SPAN = "0" * 16
_TRACEPARENT_RE = re.compile(
    r"^00-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})-[0-9a-f]{2}$")


def mint_trace_id() -> str:
    """A fresh 32-hex trace id — one per LOGICAL client request (a
    failover resubmit is the same logical request and reuses it)."""
    return os.urandom(16).hex()


def make_traceparent(trace_id: str, span_id=None) -> str:
    """Render the wire ``trace`` field. ``span_id`` is the client-side
    parent span id — an int (local tracer span id), a hex string, or
    None for "no client span" (encoded as the all-zero span id)."""
    if span_id is None:
        span = _NO_SPAN
    elif isinstance(span_id, int):
        span = format(span_id & ((1 << 64) - 1), "016x")
    else:
        span = str(span_id).lower().rjust(16, "0")[-16:]
    return f"00-{trace_id}-{span}-01"


def parse_traceparent(value) -> Tuple[str, Optional[str]]:
    """Validate a wire ``trace`` field -> ``(trace_id, parent_span)``
    with ``parent_span`` None when the client sent the all-zero span
    id. Malformed values raise :class:`ProtocolError` — a daemon must
    answer "bad trace context", never silently mis-correlate."""
    if not isinstance(value, str):
        raise ProtocolError("trace must be a traceparent string")
    m = _TRACEPARENT_RE.match(value.lower())
    if m is None:
        raise ProtocolError(
            f"trace {value!r} is not 00-<32hex>-<16hex>-<2hex>")
    tid = m.group("trace")
    if set(tid) == {"0"}:
        raise ProtocolError("trace id must not be all zeros")
    span = m.group("span")
    return tid, (None if span == _NO_SPAN else span)


@dataclass
class JobSpec:
    """One partition request, validated at the protocol boundary so the
    scheduler only ever sees well-formed work."""

    input: str
    ks: list
    tenant: str = "default"
    chunk_edges: int = 1 << 22
    dispatch_batch: int = 0        # 0 = auto (membudget-sized)
    h2d_ring: int = 0              # 0 = auto (staged H2D ring depth)
    inflight: int = 0              # 0 = auto (in-job pipeline depth)
    segment_rounds: int = 2
    alpha: float = 1.0
    weights: str = "unit"
    comm_volume: bool = False
    num_vertices: Optional[int] = None
    deadline_s: Optional[float] = None
    output: Optional[str] = None   # daemon-side partition map path
    return_assignment: bool = False
    # hold the converged partition state resident after DONE so the
    # tenant can stream delta epochs at it (ISSUE 15); the reservation
    # stays charged until released via cancel
    resident: bool = False
    # backend the resident update path folds delta epochs with
    # (ISSUE 19): multi-device names route each epoch through the
    # sharded lockstep fold + distributed rescore
    update_backend: str = "tpu"
    extra: dict = field(default_factory=dict)

    @classmethod
    def from_request(cls, body: dict, tenant: str = "default") -> "JobSpec":
        if not isinstance(body, dict):
            raise ProtocolError("job must be an object")
        if not body.get("input"):
            raise ProtocolError("job.input is required")
        ks = body.get("k", body.get("ks"))
        if isinstance(ks, int):
            ks = [ks]
        if not isinstance(ks, list) or not ks \
                or not all(isinstance(k, int) and k >= 1 for k in ks):
            raise ProtocolError("job.k must be a positive int or a "
                               "non-empty list of them")
        ks = list(dict.fromkeys(ks))  # dupes would alias result rows
        known = {"input", "k", "ks", "chunk_edges", "dispatch_batch",
                 "h2d_ring", "inflight", "segment_rounds", "alpha",
                 "weights", "comm_volume", "num_vertices", "deadline_s",
                 "output", "return_assignment", "resident",
                 "update_backend"}
        unknown = set(body) - known
        if unknown:
            raise ProtocolError(f"unknown job field(s): {sorted(unknown)}")
        spec = cls(
            input=str(body["input"]), ks=ks, tenant=str(tenant),
            chunk_edges=int(body.get("chunk_edges", 1 << 22)),
            dispatch_batch=int(body.get("dispatch_batch", 0)),
            h2d_ring=int(body.get("h2d_ring", 0)),
            inflight=int(body.get("inflight", 0)),
            segment_rounds=int(body.get("segment_rounds", 2)),
            alpha=float(body.get("alpha", 1.0)),
            weights=str(body.get("weights", "unit")),
            comm_volume=bool(body.get("comm_volume", False)),
            num_vertices=(None if body.get("num_vertices") is None
                          else int(body["num_vertices"])),
            deadline_s=(None if body.get("deadline_s") is None
                        else float(body["deadline_s"])),
            output=(None if body.get("output") is None
                    else str(body["output"])),
            return_assignment=bool(body.get("return_assignment", False)),
            resident=bool(body.get("resident", False)),
            update_backend=str(body.get("update_backend", "tpu")),
        )
        if spec.chunk_edges < 1:
            raise ProtocolError("job.chunk_edges must be >= 1")
        if spec.dispatch_batch < 0:
            raise ProtocolError("job.dispatch_batch must be >= 0 "
                               "(0 = auto)")
        if spec.h2d_ring < 0:
            raise ProtocolError("job.h2d_ring must be >= 0 (0 = auto)")
        if spec.inflight < 0:
            raise ProtocolError("job.inflight must be >= 0 (0 = auto)")
        if spec.weights not in ("unit", "degree"):
            raise ProtocolError("job.weights must be 'unit' or 'degree'")
        if spec.deadline_s is not None and spec.deadline_s <= 0:
            raise ProtocolError("job.deadline_s must be > 0 seconds")
        if spec.alpha <= 0:
            raise ProtocolError("job.alpha must be > 0")
        if spec.update_backend not in ("pure", "cpu", "tpu",
                                       "tpu-sharded", "tpu-bigv"):
            raise ProtocolError(
                "job.update_backend must be one of pure/cpu/tpu/"
                "tpu-sharded/tpu-bigv")
        return spec


def encode_edges(edges) -> dict:
    """(m, 2) int edge array -> {"b64": ..., "m": ..., "dtype":
    "int64"} — the delta payload codec of the ``update`` verb.
    Bounded by MAX_REQUEST_BYTES at the line layer (~20k edges per
    request); stream larger deltas as multiple epochs or via the
    daemon-side ``log`` form."""
    e = np.asarray(edges, dtype="<i8").reshape(-1, 2)
    return {"b64": base64.b64encode(e.tobytes()).decode("ascii"),
            "m": int(len(e)), "dtype": "int64"}


def decode_edges(doc) -> np.ndarray:
    if doc is None:
        return np.zeros((0, 2), np.int64)
    if not isinstance(doc, dict) or "b64" not in doc:
        raise ProtocolError("edge payload must be {b64, m, dtype}")
    raw = base64.b64decode(doc["b64"])
    e = np.frombuffer(raw, dtype="<i8").astype(np.int64)
    if e.size != 2 * int(doc.get("m", e.size // 2)):
        raise ProtocolError(
            f"edge payload holds {e.size // 2} pairs, header says "
            f"{doc.get('m')}")
    return e.reshape(-1, 2)


def encode_assignment(assignment) -> dict:
    """int array[V] -> {"b64": ..., "n": V, "dtype": "int32"}."""
    a = np.asarray(assignment, dtype="<i4")
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "n": int(a.size), "dtype": "int32"}


def decode_assignment(doc: dict) -> np.ndarray:
    raw = base64.b64decode(doc["b64"])
    a = np.frombuffer(raw, dtype="<i4").astype(np.int32)
    if a.size != int(doc["n"]):
        raise ProtocolError(f"assignment payload holds {a.size} entries, "
                            f"header says {doc['n']}")
    return a


def dumps(doc: dict) -> bytes:
    return (json.dumps(doc, separators=(",", ":")) + "\n").encode()


def parse_request(line: bytes) -> dict:
    if len(line) > MAX_REQUEST_BYTES:
        raise ProtocolError("request line exceeds 1 MiB")
    try:
        doc = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"bad JSON request: {e}") from None
    if not isinstance(doc, dict):
        raise ProtocolError("request must be a JSON object")
    op = doc.get("op")
    if op not in OPS:
        raise ProtocolError(f"unknown op {op!r}; want one of {OPS}")
    return doc


def read_line(sock_file) -> Optional[bytes]:
    """One protocol line from a socket makefile; None on clean EOF.
    Bounded: a peer streaming an endless unterminated line cannot grow
    memory past the request cap."""
    line = sock_file.readline(MAX_REQUEST_BYTES + 2)
    if not line:
        return None
    if not line.endswith(b"\n") and len(line) > MAX_REQUEST_BYTES:
        raise ProtocolError("unterminated request line exceeds 1 MiB")
    return line.rstrip(b"\n")
