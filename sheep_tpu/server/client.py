"""Thin sheepd client + the ``sheep-submit`` CLI verb.

    from sheep_tpu.server.client import SheepClient

    with SheepClient("/run/sheepd.sock") as c:
        jid = c.submit("graph.bin64", k=64, tenant="alice")["job_id"]
        job = c.wait(jid, timeout_s=600)
        print(job["results"][0]["edge_cut"])

Addressing: a string containing ``/`` (or ending in ``.sock``) is a
unix socket path; ``host:port`` or a bare integer is TCP. One request
per call, synchronous. The client itself is sockets + json only — it
needs no accelerator and never touches a device (the parent package's
backend registry does import jax at interpreter load; the daemon-side
machinery proper — engine/scheduler — stays un-imported here, see
``sheep_tpu/server/__init__.py``).

CLI::

    sheep-submit --server /run/sheepd.sock --input g.edges --k 8,64 \\
        --wait [--output parts.pbin] [--tenant alice] [--deadline 60]
    sheep-submit --server ... --input g.edges --k 64 --watch
    sheep-submit --server ... --input g.edges --k 64 --resident --wait
    sheep-submit --server ... --update JOB --deltas g.dlog [--wire] \\
        [--score]
    sheep-submit --server ... --epoch-of JOB | --compact JOB
    sheep-submit --server ... --status JOB | --cancel JOB | --stats \\
        | --ping | --metrics | --profile DIR | --shutdown

Incremental verbs (ISSUE 15): ``--resident`` holds the finished
partition in the daemon; ``--update JOB --deltas LOG`` applies the
log's epochs past the resident epoch (daemon-side path by default;
``--wire`` reads the log here and streams each epoch inline — the
remote-tenant shape, idempotent via explicit epoch numbers);
``--epoch-of`` / ``--compact`` query and repair; ``--cancel`` on the
DONE job releases the residency. Also reachable as ``sheep update
JOB ...`` from the main CLI.

``--watch`` (ISSUE 11) submits and then POLLS ``status`` instead of
blocking in ``wait``: live progress lines on stderr (state, phase,
steps — the descriptor's per-job progress fields), final descriptor
JSON on stdout, same exit-code contract as ``--wait``. ``--metrics``
prints the daemon's Prometheus exposition text; ``--profile DIR``
(with ``--profile-steps K``) arms an on-demand jax.profiler capture
of the next K dispatch steps into daemon-side DIR.

Failover (ISSUE 14): ``SheepClient(..., reconnect=N)`` survives a
daemon bounce — transport errors reconnect with bounded exponential
backoff (``utils/retry.RetryPolicy`` machinery, transient class) and
re-send the request. Requests are only auto-retried when re-sending
is safe: everything except a plain ``submit`` (a blind resend could
double-build) and ``shutdown``; a submit WITH ``reattach=True`` is
idempotent (the daemon matches it to the journaled job by spec
digest) and therefore retried too. ``sheep-submit`` exposes this as
``--reconnect N``, defaulting ON for ``--watch`` so a daemon restart
mid-watch keeps the progress lines flowing instead of dying with a
connection error — the exit-code contract is unchanged.

Exit codes: 0 op succeeded (for --wait/--watch: job DONE), 1 usage/
transport, 2 daemon answered ok=false, 3 job reached a non-done
terminal state (failed / cancelled / deadline_exceeded / rejected),
4 --wait's/--watch's --timeout elapsed with the job still queued/
running (not terminal — do not resubmit).
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
from typing import Optional

from sheep_tpu.server import protocol


def _connect(server: str, timeout_s: float) -> socket.socket:
    server = str(server)
    if "/" in server or server.endswith(".sock"):
        s = socket.socket(socket.AF_UNIX)
        s.settimeout(timeout_s)
        s.connect(server)
        return s
    host, _, port = server.rpartition(":")
    try:
        port_n = int(port)
    except ValueError:
        raise ServerError(
            f"bad --server address {server!r}: want a unix socket path "
            f"(contains '/') or host:port") from None
    s = socket.create_connection((host or "127.0.0.1", port_n),
                                 timeout=timeout_s)
    return s


class SheepClient:
    """One connection to a sheepd; methods mirror the protocol ops and
    return the daemon's response body (raising :class:`ServerError`
    on ok=false). ``reconnect`` arms bounded transport failover (see
    module docstring); 0 keeps the classic fail-fast behavior."""

    def __init__(self, server: str, timeout_s: float = 600.0,
                 reconnect: int = 0, reconnect_base_s: float = 0.2):
        self.server = server
        self.timeout_s = timeout_s
        self.reconnect = int(reconnect)
        self._reconnect_base_s = float(reconnect_base_s)
        self._sock = None
        self._rf = None
        pol = self._policy()
        while True:
            try:
                self._open()
                return
            except OSError as e:
                # the restart window starts before the first connect:
                # a client launched while the daemon bounces should
                # wait for it, not die on ECONNREFUSED
                self._retry_or_raise(pol, e, "connect")

    def _policy(self):
        from sheep_tpu.utils import retry as retry_mod

        return retry_mod.RetryPolicy(max_retries=self.reconnect,
                                     base_delay_s=self._reconnect_base_s,
                                     max_delay_s=5.0)

    def _retry_or_raise(self, policy, exc, where: str) -> None:
        from sheep_tpu.utils import retry as retry_mod

        if policy is None or not policy.admit(retry_mod.TRANSIENT):
            raise exc
        policy.backoff(retry_mod.TRANSIENT, exc,
                       where=f"sheep-client.{where}")

    def _open(self) -> None:
        self._sock = _connect(self.server, self.timeout_s)
        self._rf = self._sock.makefile("rb")

    def _drop(self) -> None:
        try:
            if self._rf is not None:
                self._rf.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._rf = None
        self._sock = None

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "SheepClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    @staticmethod
    def _retriable(doc: dict) -> bool:
        """Safe to blindly re-send after a transport error: everything
        except a plain submit (double-build risk — reattach makes it
        idempotent and thus retriable), an un-epoched update (a blind
        resend could double-fold; explicit epochs and the log form are
        idempotent — the daemon answers applied=false for an epoch it
        already holds), compact (double-compacting is observable), and
        shutdown."""
        op = doc.get("op")
        if op == "submit":
            return bool(doc.get("reattach"))
        if op == "update":
            return doc.get("epoch") is not None \
                or doc.get("log") is not None
        return op not in ("shutdown", "compact")

    def request(self, doc: dict) -> dict:
        pol = self._policy() if self.reconnect > 0 \
            and self._retriable(doc) else None
        while True:
            try:
                if self._sock is None:
                    self._open()
                self._sock.sendall(protocol.dumps(doc))
                line = self._rf.readline()
                if not line:
                    raise ConnectionResetError(
                        "connection closed by daemon")
                resp = json.loads(line)
            except (OSError, json.JSONDecodeError) as e:
                self._drop()
                if isinstance(e, ConnectionResetError) and pol is None:
                    # the classic (reconnect=0) contract: a daemon
                    # that hangs up mid-request answers as a daemon
                    # error, not a transport one
                    raise ServerError(str(e)) from None
                self._retry_or_raise(pol, e,
                                     str(doc.get("op", "request")))
                continue
            if not resp.get("ok"):
                raise ServerError(resp.get("error",
                                           "unknown daemon error"))
            return resp

    # -- ops -----------------------------------------------------------
    def ping(self) -> dict:
        return self.request({"op": "ping"})

    def submit(self, input: str, k, tenant: str = "default",
               reattach: bool = False, **job_fields) -> dict:
        """``reattach=True`` makes the submit idempotent: the daemon
        matches the spec digest against existing jobs (journaled ones
        included) and returns the live/completed twin — with
        ``"reattached": true`` in the response — instead of building
        again. The safe shape for retried submits across a daemon
        restart."""
        job = {"input": input, "k": k, **job_fields}
        req = {"op": "submit", "tenant": tenant, "job": job}
        if reattach:
            req["reattach"] = True
        return self.request(req)

    def status(self, job_id: str) -> dict:
        return self.request({"op": "status", "job_id": job_id})["job"]

    def wait(self, job_id: str,
             timeout_s: Optional[float] = None) -> dict:
        return self.request({"op": "wait", "job_id": job_id,
                             "timeout_s": timeout_s})["job"]

    def cancel(self, job_id: str) -> str:
        return self.request({"op": "cancel",
                             "job_id": job_id})["state"]

    def list(self) -> list:
        return self.request({"op": "list"})["jobs"]

    def stats(self) -> dict:
        return self.request({"op": "stats"})["stats"]

    def metrics(self) -> str:
        """The daemon's live Prometheus exposition text (same document
        as HTTP GET /metrics on --metrics-port)."""
        return self.request({"op": "metrics"})["text"]

    # -- resident-partition verbs (ISSUE 15) ---------------------------
    def update(self, job_id: str, adds=None, dels=None,
               epoch: Optional[int] = None, score: bool = False,
               compact: str = "auto",
               log: Optional[str] = None) -> dict:
        """Stream one delta epoch at a resident partition: ``adds`` /
        ``dels`` are (m, 2) edge arrays (base64 on the wire, bounded
        by the 1 MiB request line), or ``log`` names a DAEMON-side
        delta log whose epochs past the resident epoch all apply.
        Explicit ``epoch`` numbers make the call idempotent (an
        already-applied epoch answers ``applied: false``)."""
        req = {"op": "update", "job_id": job_id,
               "score": bool(score), "compact": compact}
        if adds is not None:
            req["adds"] = protocol.encode_edges(adds)
        if dels is not None:
            req["dels"] = protocol.encode_edges(dels)
        if epoch is not None:
            req["epoch"] = int(epoch)
        if log is not None:
            req["log"] = log
        return self.request(req)

    def epoch(self, job_id: str) -> dict:
        """Resident-partition epoch/staleness descriptor."""
        return self.request({"op": "epoch", "job_id": job_id})

    def compact(self, job_id: str, mode: str = "auto",
                score: bool = False) -> dict:
        """Run tombstone compaction on a resident partition."""
        return self.request({"op": "compact", "job_id": job_id,
                             "mode": mode, "score": bool(score)})

    def profile(self, dir: str, steps: int = 8) -> dict:
        """Arm an on-demand jax.profiler capture of the next ``steps``
        dispatch steps into daemon-side directory ``dir``; completion
        is queryable via :meth:`stats`'s ``profile`` field."""
        return self.request({"op": "profile", "dir": dir,
                             "steps": steps})["profile"]

    def shutdown(self, drain: bool = False) -> dict:
        return self.request({"op": "shutdown", "drain": drain})

    def result_assignment(self, job: dict, k: Optional[int] = None):
        """Decode the packed assignment for part count ``k`` (default:
        the job's first) from a wait/status descriptor — only present
        when the job was submitted with ``return_assignment``."""
        for row in job.get("results") or []:
            if k is None or row.get("k") == k:
                if "assignment" not in row:
                    break
                return protocol.decode_assignment(row["assignment"])
        raise ServerError(
            f"job {job.get('job_id')} carries no assignment for k={k} "
            f"(submit with return_assignment=true)")


class ServerError(RuntimeError):
    """The daemon answered ok=false (or went away mid-request)."""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sheep-submit",
        description="submit partition jobs to a running sheepd")
    p.add_argument("--server", required=True,
                   help="daemon address: unix socket path or host:port")
    p.add_argument("--input", help="graph path or synthetic spec "
                                   "(as the main CLI's --input)")
    p.add_argument("--k", help="part count, or comma list for multi-k "
                               "from one shared tree")
    p.add_argument("--tenant", default="default")
    p.add_argument("--chunk-edges", type=int, default=None)
    p.add_argument("--dispatch-batch", type=int, default=None)
    p.add_argument("--h2d-ring", type=int, default=None,
                   help="staged H2D ring depth for host-format inputs "
                        "(0 = auto; device-generated specs skip "
                        "staging)")
    p.add_argument("--alpha", type=float, default=None)
    p.add_argument("--weights", choices=["unit", "degree"], default=None)
    p.add_argument("--comm-volume", action="store_true")
    p.add_argument("--num-vertices", type=int, default=None)
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="seconds from submit until the job must be "
                        "done (expired -> deadline_exceeded)")
    p.add_argument("--output", default=None,
                   help="daemon-side partition map path (.parts/.pbin)")
    p.add_argument("--wait", action="store_true",
                   help="block until the job is terminal; print its "
                        "descriptor; exit 0 only on done")
    p.add_argument("--watch", action="store_true",
                   help="like --wait but poll status and render live "
                        "progress lines (state/phase/steps) on stderr "
                        "instead of blocking silently")
    p.add_argument("--poll", type=float, default=0.5, metavar="S",
                   help="with --watch: poll interval (default 0.5s)")
    p.add_argument("--reconnect", type=int, default=None, metavar="N",
                   help="survive a daemon bounce: retry transport "
                        "errors up to N times with exponential "
                        "backoff, re-sending idempotent requests "
                        "(submits reattach to the journaled job by "
                        "digest instead of double-building). Default: "
                        "8 with --watch, else 0")
    p.add_argument("--timeout", type=float, default=None,
                   help="with --wait/--watch: give up after this many "
                        "seconds")
    p.add_argument("--resident", action="store_true",
                   help="with --input: hold the finished partition "
                        "RESIDENT in the daemon so delta epochs can "
                        "stream at it (--update); the admission "
                        "reservation stays charged until --cancel "
                        "releases it")
    p.add_argument("--update", metavar="JOB", default=None,
                   help="apply delta epochs to a resident partition; "
                        "needs --deltas LOG (daemon-side path by "
                        "default, --wire streams each epoch inline)")
    p.add_argument("--deltas", metavar="LOG", default=None,
                   help="with --update: the delta log "
                        "(io/deltalog.py) whose epochs past the "
                        "resident epoch apply")
    p.add_argument("--wire", action="store_true",
                   help="with --update: read the log CLIENT-side and "
                        "stream each epoch as an inline update "
                        "request (the remote-tenant path; default "
                        "sends the daemon-side log path)")
    p.add_argument("--score", action="store_true",
                   help="with --update/--compact: refresh + return "
                        "the scored results after applying")
    p.add_argument("--epoch-of", metavar="JOB", default=None,
                   help="print a resident partition's epoch/staleness "
                        "descriptor")
    p.add_argument("--compact", metavar="JOB", default=None,
                   help="compact a resident partition's tombstones")
    p.add_argument("--compact-mode", default="auto",
                   choices=["auto", "full", "subtree"],
                   help="with --compact: full re-anchors and rebuilds "
                        "everything (exact), subtree repairs only the "
                        "dirty tree-split parts (score-bounded), auto "
                        "picks (default)")
    p.add_argument("--status", metavar="JOB")
    p.add_argument("--cancel", metavar="JOB")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--ping", action="store_true")
    p.add_argument("--metrics", action="store_true",
                   help="print the daemon's live Prometheus text")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="arm an on-demand jax.profiler capture into "
                        "daemon-side DIR")
    p.add_argument("--profile-steps", type=int, default=8, metavar="K",
                   help="with --profile: capture the next K dispatch "
                        "steps (default 8)")
    p.add_argument("--shutdown", action="store_true")
    p.add_argument("--drain", action="store_true",
                   help="with --shutdown: finish accepted jobs first")
    return p


def _watch_job(c: "SheepClient", job_id: str, poll_s: float,
               timeout_s: Optional[float]) -> dict:
    """Poll status until terminal (or timeout), rendering one progress
    line per change on stderr; returns the last descriptor. Daemon
    bounces are absorbed below in ``request`` when the client was
    built with ``reconnect`` (the --watch default): each poll retries
    transports with backoff, so a restarting daemon shows up as a few
    stderr retry notes and then the resumed job's progress — not a
    dead watch."""
    import time

    t0 = time.monotonic()
    deadline = None if timeout_s is None else t0 + timeout_s
    last_line = None
    while True:
        desc = c.status(job_id)
        state = desc.get("state")
        bits = [f"{time.monotonic() - t0:7.1f}s", job_id, state]
        if desc.get("phase"):
            bits.append(f"phase={desc['phase']}")
        if desc.get("steps"):
            bits.append(f"steps={desc['steps']}")
        if state == "done" and desc.get("results"):
            r = desc["results"][0]
            bits.append(f"cut_ratio={r.get('cut_ratio')}")
        if desc.get("error"):
            bits.append(f"error={desc['error'][:120]}")
        line = " ".join(bits)
        if line != last_line:
            print(f"sheep-submit: {line}", file=sys.stderr, flush=True)
            last_line = line
        if state in protocol.TERMINAL_STATES:
            return desc
        if deadline is not None and time.monotonic() >= deadline:
            return desc
        time.sleep(max(0.05, poll_s))


def main(argv=None) -> int:
    p = build_parser()
    args = p.parse_args(argv)
    modes = [bool(args.input), bool(args.status), bool(args.cancel),
             args.stats, args.ping, args.shutdown, args.metrics,
             bool(args.profile), bool(args.update),
             bool(args.epoch_of), bool(args.compact)]
    if sum(modes) != 1:
        p.error("pass exactly one of --input (submit), --status, "
                "--cancel, --stats, --ping, --metrics, --profile, "
                "--update, --epoch-of, --compact, --shutdown")
    if args.update and not args.deltas:
        p.error("--update needs --deltas LOG")
    reconnect = args.reconnect if args.reconnect is not None \
        else (8 if args.watch else 0)
    if reconnect < 0:
        p.error("--reconnect must be >= 0")
    try:
        with SheepClient(args.server, reconnect=reconnect) as c:
            if args.ping:
                print(json.dumps(c.ping()))
                return 0
            if args.stats:
                print(json.dumps(c.stats(), indent=1))
                return 0
            if args.metrics:
                sys.stdout.write(c.metrics())
                return 0
            if args.profile:
                print(json.dumps(c.profile(args.profile,
                                           steps=args.profile_steps)))
                return 0
            if args.shutdown:
                print(json.dumps(c.shutdown(drain=args.drain)))
                return 0
            if args.epoch_of:
                print(json.dumps(c.epoch(args.epoch_of)))
                return 0
            if args.compact:
                print(json.dumps(c.compact(args.compact,
                                           mode=args.compact_mode,
                                           score=args.score)))
                return 0
            if args.update:
                if args.wire:
                    # remote-tenant path: read the log HERE, stream
                    # each epoch inline (idempotent: explicit epoch
                    # numbers — an already-applied epoch is a no-op)
                    from sheep_tpu.io.deltalog import DeltaLogReader

                    cur = int(c.epoch(args.update)["epoch"])
                    resp = {"job_id": args.update, "epoch": cur,
                            "applied": False, "epochs_applied": 0}
                    applied = 0
                    reader = DeltaLogReader(args.deltas)
                    mx = reader.max_epoch  # records() cached: 1 read
                    for ep, adds, dels in reader.epochs(
                            start_epoch=cur):
                        resp = c.update(args.update, adds=adds,
                                        dels=dels, epoch=ep,
                                        score=args.score and ep == mx)
                        applied += resp.get("epochs_applied", 0)
                    resp["epochs_applied"] = applied
                    resp["applied"] = applied > 0
                else:
                    resp = c.update(args.update, log=args.deltas,
                                    score=args.score)
                print(json.dumps(resp))
                return 0
            if args.status:
                print(json.dumps(c.status(args.status)))
                return 0
            if args.cancel:
                print(json.dumps({"job_id": args.cancel,
                                  "state": c.cancel(args.cancel)}))
                return 0
            # submit
            if not args.k:
                p.error("--input needs --k")
            try:
                ks = [int(x) for x in str(args.k).split(",") if x != ""]
            except ValueError:
                ks = []
            if not ks or any(k < 1 for k in ks):
                p.error(f"--k must be a positive int or comma list "
                        f"(got {args.k!r})")
            job = {"k": ks}
            for field, val in (("chunk_edges", args.chunk_edges),
                               ("dispatch_batch", args.dispatch_batch),
                               ("h2d_ring", args.h2d_ring),
                               ("alpha", args.alpha),
                               ("weights", args.weights),
                               ("num_vertices", args.num_vertices),
                               ("deadline_s", args.deadline),
                               ("output", args.output)):
                if val is not None:
                    job[field] = val
            if args.comm_volume:
                job["comm_volume"] = True
            if args.resident:
                job["resident"] = True
            # with failover armed the submit itself must be idempotent
            # (the retried submit against a restarted daemon reattaches
            # to the journaled job instead of double-building)
            resp = c.submit(args.input, tenant=args.tenant,
                            reattach=reconnect > 0, **job)
            if not (args.wait or args.watch):
                print(json.dumps(resp))
                return 0
            if args.watch:
                desc = _watch_job(c, resp["job_id"], args.poll,
                                  args.timeout)
            else:
                desc = c.wait(resp["job_id"], timeout_s=args.timeout)
            print(json.dumps(desc))
            if desc.get("state") == "done":
                return 0
            if desc.get("state") in ("queued", "running"):
                # --timeout elapsed with the job still in flight: NOT a
                # terminal failure — a supervisor must not resubmit
                print(f"sheep-submit: wait timed out; job "
                      f"{desc.get('job_id')} is still "
                      f"{desc.get('state')}", file=sys.stderr)
                return 4
            return 3
    except (ServerError, OSError, json.JSONDecodeError) as e:
        kind = "daemon" if isinstance(e, ServerError) else "transport"
        print(f"sheep-submit: {kind} error: {e}", file=sys.stderr)
        return 2 if isinstance(e, ServerError) else 1


if __name__ == "__main__":
    sys.exit(main())
