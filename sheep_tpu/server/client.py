"""Thin sheepd client + the ``sheep-submit`` CLI verb.

    from sheep_tpu.server.client import SheepClient

    with SheepClient("/run/sheepd.sock") as c:
        jid = c.submit("graph.bin64", k=64, tenant="alice")["job_id"]
        job = c.wait(jid, timeout_s=600)
        print(job["results"][0]["edge_cut"])

Addressing: a string containing ``/`` (or ending in ``.sock``) is a
unix socket path; ``host:port`` or a bare integer is TCP. One request
per call, synchronous. The client itself is sockets + json only — it
needs no accelerator and never touches a device (the parent package's
backend registry does import jax at interpreter load; the daemon-side
machinery proper — engine/scheduler — stays un-imported here, see
``sheep_tpu/server/__init__.py``).

CLI::

    sheep-submit --server /run/sheepd.sock --input g.edges --k 8,64 \\
        --wait [--output parts.pbin] [--tenant alice] [--deadline 60]
    sheep-submit --server ... --input g.edges --k 64 --watch
    sheep-submit --server ... --input g.edges --k 64 --resident --wait
    sheep-submit --server ... --update JOB --deltas g.dlog [--wire] \\
        [--score]
    sheep-submit --server ... --epoch-of JOB | --compact JOB
    sheep-submit --server ... --status JOB | --cancel JOB | --stats \\
        | --ping | --metrics | --profile DIR | --shutdown

Incremental verbs (ISSUE 15): ``--resident`` holds the finished
partition in the daemon; ``--update JOB --deltas LOG`` applies the
log's epochs past the resident epoch (daemon-side path by default;
``--wire`` reads the log here and streams each epoch inline — the
remote-tenant shape, idempotent via explicit epoch numbers);
``--epoch-of`` / ``--compact`` query and repair; ``--cancel`` on the
DONE job releases the residency. Also reachable as ``sheep update
JOB ...`` from the main CLI.

``--watch`` (ISSUE 11) submits and then POLLS ``status`` instead of
blocking in ``wait``: live progress lines on stderr (state, phase,
steps — the descriptor's per-job progress fields), final descriptor
JSON on stdout, same exit-code contract as ``--wait``. ``--metrics``
prints the daemon's Prometheus exposition text; ``--profile DIR``
(with ``--profile-steps K``) arms an on-demand jax.profiler capture
of the next K dispatch steps into daemon-side DIR.

Failover (ISSUE 14): ``SheepClient(..., reconnect=N)`` survives a
daemon bounce — transport errors reconnect with bounded exponential
backoff (``utils/retry.RetryPolicy`` machinery, transient class) and
re-send the request. Requests are only auto-retried when re-sending
is safe: everything except a plain ``submit`` (a blind resend could
double-build) and ``shutdown``; a submit WITH ``reattach=True`` is
idempotent (the daemon matches it to the journaled job by spec
digest) and therefore retried too. ``sheep-submit`` exposes this as
``--reconnect N``, defaulting ON for ``--watch`` so a daemon restart
mid-watch keeps the progress lines flowing instead of dying with a
connection error — the exit-code contract is unchanged.

Fleet mode (ISSUE 16): ``--endpoints a.sock,b.sock`` replaces
``--server`` with a comma list of replica addresses and routes the
submit through :class:`FleetClient` — a result-cache ``lookup`` of
the spec digest on every live replica first (a hit is answered with
zero build steps, so it short-circuits routing), then the replica
with the shallowest queue / largest admission headroom (scraped from
the live metrics gauges). A replica that dies while the job is being
waited on gets the job re-submitted — ``reattach``-idempotent — to
the next live replica; per-replica route counters land in the obs
trace as ``fleet_route`` events. Fleet mode covers the submit family
(``--wait`` / ``--watch`` included) and, since ISSUE 17, the
resident verbs: ``--update`` / ``--epoch-of`` / ``--compact`` route
to the replica OWNING the resident job (pinned after a status sweep)
and deliberately never fail over — resident state is replica-local.
Other admin verbs still address one replica via ``--server``.

Fleet observability (ISSUE 18): every submit mints a
W3C-traceparent-shaped trace context (``protocol.make_traceparent``)
sent as the request's ``trace`` field and re-sent on every later
wait/status/cancel/update naming that job; a FleetClient failover
resubmit REUSES the logical request's trace, so one trace id
correlates the client's ``fleet_request``/``fleet_failover`` spans
and every replica's job spans (``trace_report --stitch`` renders the
cross-process tree). The routing scrape is TTL-cached
(``SHEEP_FLEET_SCRAPE_TTL_S``, default 1 s) so submit bursts pay one
``/metrics`` round-trip per replica per window, with scrape wall cost
on the ``fleet_scrape_ms`` obs counter.

Chunked updates (ISSUE 17): :meth:`SheepClient.update` payloads too
large for the 1 MiB request line switch automatically to a
``begin`` / ``chunk`` / ``commit`` transaction over one connection,
applied by the daemon as ONE epoch at commit — a single call streams
an arbitrarily large epoch, and a client death mid-stream (no
commit) leaves the resident at its prior epoch, retryable from
scratch.

CLI (fleet)::

    sheep-submit --endpoints /run/a.sock,/run/b.sock \\
        --input g.edges --k 64 --wait

Exit codes: 0 op succeeded (for --wait/--watch: job DONE), 1 usage/
transport, 2 daemon answered ok=false, 3 job reached a non-done
terminal state (failed / cancelled / deadline_exceeded / rejected),
4 --wait's/--watch's --timeout elapsed with the job still queued/
running (not terminal — do not resubmit).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
from typing import Optional

from sheep_tpu.server import protocol

# chunked-update slicing (ISSUE 17): 32768 edges base64-encode to
# ~700 KiB — comfortably under protocol.MAX_REQUEST_BYTES per line
UPDATE_CHUNK_EDGES = 32768


def _connect(server: str, timeout_s: float) -> socket.socket:
    server = str(server)
    if "/" in server or server.endswith(".sock"):
        s = socket.socket(socket.AF_UNIX)
        s.settimeout(timeout_s)
        s.connect(server)
        return s
    host, _, port = server.rpartition(":")
    try:
        port_n = int(port)
    except ValueError:
        raise ServerError(
            f"bad --server address {server!r}: want a unix socket path "
            f"(contains '/') or host:port") from None
    s = socket.create_connection((host or "127.0.0.1", port_n),
                                 timeout=timeout_s)
    return s


class SheepClient:
    """One connection to a sheepd; methods mirror the protocol ops and
    return the daemon's response body (raising :class:`ServerError`
    on ok=false). ``reconnect`` arms bounded transport failover (see
    module docstring); 0 keeps the classic fail-fast behavior."""

    def __init__(self, server: str, timeout_s: float = 600.0,
                 reconnect: int = 0, reconnect_base_s: float = 0.2):
        self.server = server
        self.timeout_s = timeout_s
        self.reconnect = int(reconnect)
        self._reconnect_base_s = float(reconnect_base_s)
        self._sock = None
        self._rf = None
        # job_id -> the traceparent minted at submit (ISSUE 18): every
        # later wait/status/cancel/update on that job re-sends the
        # SAME trace context, so the whole logical request correlates
        self._job_traces: dict = {}
        pol = self._policy()
        while True:
            try:
                self._open()
                return
            except OSError as e:
                # the restart window starts before the first connect:
                # a client launched while the daemon bounces should
                # wait for it, not die on ECONNREFUSED
                self._retry_or_raise(pol, e, "connect")

    def _policy(self):
        from sheep_tpu.utils import retry as retry_mod

        return retry_mod.RetryPolicy(max_retries=self.reconnect,
                                     base_delay_s=self._reconnect_base_s,
                                     max_delay_s=5.0)

    def _retry_or_raise(self, policy, exc, where: str) -> None:
        from sheep_tpu.utils import retry as retry_mod

        if policy is None or not policy.admit(retry_mod.TRANSIENT):
            raise exc
        policy.backoff(retry_mod.TRANSIENT, exc,
                       where=f"sheep-client.{where}")

    def _open(self) -> None:
        self._sock = _connect(self.server, self.timeout_s)
        self._rf = self._sock.makefile("rb")

    def _drop(self) -> None:
        try:
            if self._rf is not None:
                self._rf.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._rf = None
        self._sock = None

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "SheepClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    @staticmethod
    def _retriable(doc: dict) -> bool:
        """Safe to blindly re-send after a transport error: everything
        except a plain submit (double-build risk — reattach makes it
        idempotent and thus retriable), an un-epoched update (a blind
        resend could double-fold; explicit epochs and the log form are
        idempotent — the daemon answers applied=false for an epoch it
        already holds), compact (double-compacting is observable), and
        shutdown."""
        op = doc.get("op")
        if op == "submit":
            return bool(doc.get("reattach"))
        if op == "update":
            if doc.get("stream") is not None:
                # chunked sub-verbs are transaction-scoped: resending
                # one on a FRESH connection can only hit "unknown
                # txn" — the whole-transaction retry in
                # _update_chunked owns recovery instead
                return False
            return doc.get("epoch") is not None \
                or doc.get("log") is not None
        return op not in ("shutdown", "compact")

    def request(self, doc: dict) -> dict:
        if "trace" not in doc:
            tp = self._job_traces.get(doc.get("job_id"))
            if tp is not None:
                doc = dict(doc, trace=tp)
        pol = self._policy() if self.reconnect > 0 \
            and self._retriable(doc) else None
        while True:
            try:
                if self._sock is None:
                    self._open()
                self._sock.sendall(protocol.dumps(doc))
                line = self._rf.readline()
                if not line:
                    raise ConnectionResetError(
                        "connection closed by daemon")
                resp = json.loads(line)
            except (OSError, json.JSONDecodeError) as e:
                self._drop()
                if isinstance(e, ConnectionResetError) and pol is None:
                    # the classic (reconnect=0) contract: a daemon
                    # that hangs up mid-request answers as a daemon
                    # error, not a transport one
                    raise ServerError(str(e)) from None
                self._retry_or_raise(pol, e,
                                     str(doc.get("op", "request")))
                continue
            if not resp.get("ok"):
                raise ServerError(resp.get("error",
                                           "unknown daemon error"))
            return resp

    # -- ops -----------------------------------------------------------
    def ping(self) -> dict:
        return self.request({"op": "ping"})

    def _mint_trace(self) -> str:
        """One fresh wire trace context per logical request (ISSUE
        18), parented to the calling thread's current obs span when
        one is open — the daemon's job span then stitches under it
        (``trace_report --stitch``)."""
        from sheep_tpu import obs

        return protocol.make_traceparent(protocol.mint_trace_id(),
                                         obs.current_span_id())

    def submit(self, input: str, k, tenant: str = "default",
               reattach: bool = False, trace: Optional[str] = None,
               **job_fields) -> dict:
        """``reattach=True`` makes the submit idempotent: the daemon
        matches the spec digest against existing jobs (journaled ones
        included) and returns the live/completed twin — with
        ``"reattached": true`` in the response — instead of building
        again. The safe shape for retried submits across a daemon
        restart.

        ``trace`` overrides the wire trace context (a FleetClient
        failover resubmit reuses the logical request's); by default a
        fresh one is minted per submit and re-sent on every later
        request naming the returned job id."""
        job = {"input": input, "k": k, **job_fields}
        req = {"op": "submit", "tenant": tenant, "job": job,
               "trace": trace or self._mint_trace()}
        if reattach:
            req["reattach"] = True
        resp = self.request(req)
        jid = resp.get("job_id")
        if jid:
            self._job_traces[jid] = req["trace"]
        return resp

    def status(self, job_id: str) -> dict:
        return self.request({"op": "status", "job_id": job_id})["job"]

    def wait(self, job_id: str,
             timeout_s: Optional[float] = None) -> dict:
        return self.request({"op": "wait", "job_id": job_id,
                             "timeout_s": timeout_s})["job"]

    def cancel(self, job_id: str) -> str:
        return self.request({"op": "cancel",
                             "job_id": job_id})["state"]

    def list(self) -> list:
        return self.request({"op": "list"})["jobs"]

    def stats(self) -> dict:
        return self.request({"op": "stats"})["stats"]

    def metrics(self) -> str:
        """The daemon's live Prometheus exposition text (same document
        as HTTP GET /metrics on --metrics-port)."""
        return self.request({"op": "metrics"})["text"]

    def lookup(self, digest: str) -> bool:
        """Advisory result-cache probe (ISSUE 16): True when the
        daemon can answer a submit with this spec digest straight
        from its result store — zero build steps, zero compiles. See
        :func:`fleet_digest` for computing the digest client-side."""
        return bool(self.request({"op": "lookup",
                                  "digest": digest})["hit"])

    # -- resident-partition verbs (ISSUE 15) ---------------------------
    def update(self, job_id: str, adds=None, dels=None,
               epoch: Optional[int] = None, score: bool = False,
               compact: str = "auto", log: Optional[str] = None,
               chunk_edges: Optional[int] = None) -> dict:
        """Stream one delta epoch at a resident partition: ``adds`` /
        ``dels`` are (m, 2) edge arrays (base64 on the wire), or
        ``log`` names a DAEMON-side delta log whose epochs past the
        resident epoch all apply. Explicit ``epoch`` numbers make the
        call idempotent (an already-applied epoch answers
        ``applied: false``).

        Payloads too large for the 1 MiB request line switch to the
        chunked wire form automatically (ISSUE 17): one begin /
        chunk* / commit transaction over this connection, applied by
        the daemon as ONE epoch at commit — so a single call streams
        an arbitrarily large epoch. ``chunk_edges`` overrides the
        per-chunk edge count (default ``UPDATE_CHUNK_EDGES``)."""
        ce = int(chunk_edges) if chunk_edges else UPDATE_CHUNK_EDGES
        n = (0 if adds is None else len(adds)) \
            + (0 if dels is None else len(dels))
        if log is None and n > ce:
            return self._update_chunked(job_id, adds, dels, epoch,
                                        score, compact, ce)
        req = {"op": "update", "job_id": job_id,
               "score": bool(score), "compact": compact}
        if adds is not None:
            req["adds"] = protocol.encode_edges(adds)
        if dels is not None:
            req["dels"] = protocol.encode_edges(dels)
        if epoch is not None:
            req["epoch"] = int(epoch)
        if log is not None:
            req["log"] = log
        return self.request(req)

    def _update_chunked(self, job_id: str, adds, dels, epoch,
                        score: bool, compact: str,
                        chunk_edges: int) -> dict:
        """One chunked update transaction. Retries (when armed AND the
        epoch is explicit, i.e. idempotent) restart from ``begin``:
        transactions are connection-scoped, so a transport drop
        anywhere mid-stream discards the staged chunks server-side
        and the only safe resume point is a fresh transaction."""
        pol = self._policy() if self.reconnect > 0 \
            and epoch is not None else None
        while True:
            try:
                txn = self.request({"op": "update", "job_id": job_id,
                                    "stream": "begin"})["txn"]
                for key, arr in (("adds", adds), ("dels", dels)):
                    if arr is None:
                        continue
                    for lo in range(0, len(arr), chunk_edges):
                        part = arr[lo:lo + chunk_edges]
                        self.request({
                            "op": "update", "stream": "chunk",
                            "txn": txn,
                            key: protocol.encode_edges(part)})
                commit = {"op": "update", "stream": "commit",
                          "txn": txn, "score": bool(score),
                          "compact": compact}
                if epoch is not None:
                    commit["epoch"] = int(epoch)
                return self.request(commit)
            except (OSError, ServerError) as e:
                if isinstance(e, ServerError) \
                        and "connection closed" not in str(e) \
                        and "unknown update txn" not in str(e):
                    raise  # a real daemon answer, not a torn stream
                if pol is None:
                    raise
                self._drop()
                self._retry_or_raise(pol, e, "update.stream")

    def epoch(self, job_id: str) -> dict:
        """Resident-partition epoch/staleness descriptor."""
        return self.request({"op": "epoch", "job_id": job_id})

    def compact(self, job_id: str, mode: str = "auto",
                score: bool = False) -> dict:
        """Run tombstone compaction on a resident partition."""
        return self.request({"op": "compact", "job_id": job_id,
                             "mode": mode, "score": bool(score)})

    def profile(self, dir: str, steps: int = 8) -> dict:
        """Arm an on-demand jax.profiler capture of the next ``steps``
        dispatch steps into daemon-side directory ``dir``; completion
        is queryable via :meth:`stats`'s ``profile`` field."""
        return self.request({"op": "profile", "dir": dir,
                             "steps": steps})["profile"]

    def shutdown(self, drain: bool = False) -> dict:
        return self.request({"op": "shutdown", "drain": drain})

    def result_assignment(self, job: dict, k: Optional[int] = None):
        """Decode the packed assignment for part count ``k`` (default:
        the job's first) from a wait/status descriptor — only present
        when the job was submitted with ``return_assignment``."""
        for row in job.get("results") or []:
            if k is None or row.get("k") == k:
                if "assignment" not in row:
                    break
                return protocol.decode_assignment(row["assignment"])
        raise ServerError(
            f"job {job.get('job_id')} carries no assignment for k={k} "
            f"(submit with return_assignment=true)")


class ServerError(RuntimeError):
    """The daemon answered ok=false (or went away mid-request)."""


def fleet_digest(input: str, k, tenant: str = "default",
                 **job_fields) -> str:
    """The spec digest a daemon would journal for this submit,
    computed CLIENT-side through the same ``JobSpec.from_request`` +
    ``journal.job_digest`` pair the daemon runs (the digest folds in
    the input file's size/mtime via os.stat, so it matches when
    client and daemons see the same filesystem — the unix-socket
    fleet shape). This is the result-cache / reattach key: any
    replica holding it answers the submit without building."""
    from sheep_tpu.server import journal as journal_mod

    job = {"input": input, "k": k, **job_fields}
    spec = protocol.JobSpec.from_request(job, tenant=tenant)
    return journal_mod.job_digest(spec)


def _trace_id_of(traceparent: Optional[str]) -> Optional[str]:
    """The bare 32-hex trace id out of a wire traceparent (None when
    absent/malformed) — what grep-able obs events carry."""
    if not traceparent:
        return None
    try:
        return protocol.parse_traceparent(traceparent)[0]
    except protocol.ProtocolError:
        return None


class FleetClient:
    """Routes submits across a fleet of sheepd replicas (ISSUE 16).

    Per submit, in order:

    1. digest short-circuit — every live replica answers ``lookup``
       for the spec digest; a result-cache hit routes the submit
       straight there (it completes with zero build steps);
    2. headroom routing — otherwise the submit goes to the replica
       with the least load, ordered by queued+active jobs then by
       largest admission headroom, both scraped from the live
       metrics gauges (``sheepd_queue_depth`` +
       ``sheepd_active_jobs``, ``sheepd_headroom_bytes``);
    3. failover — a replica that dies while one of its jobs is being
       waited on (or status-polled) gets that job re-submitted to
       the next live replica. Failover resubmits carry
       ``reattach=True`` (a bounced-but-journaled daemon reattaches
       instead of double-building); FIRST submits are plain, so a
       repeat request reaches the result store instead of
       reattaching to a retained terminal twin.

    ``route_counts`` tallies submits per endpoint; every routing
    decision also lands in the obs trace as a ``fleet_route`` event
    with the running counters. ``reconnect`` is the per-endpoint
    transport retry budget (as :class:`SheepClient`); the default 0
    fails fast into the failover path, which is usually what a fleet
    wants — a *dead* replica should not be backed off against when a
    live one can take the job.
    """

    def __init__(self, endpoints, timeout_s: float = 600.0,
                 reconnect: int = 0, reconnect_base_s: float = 0.2):
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        eps = [str(e).strip() for e in endpoints if str(e).strip()]
        if not eps:
            raise ValueError("FleetClient needs at least one endpoint")
        self.endpoints = eps
        self.timeout_s = float(timeout_s)
        self.reconnect = int(reconnect)
        self._reconnect_base_s = float(reconnect_base_s)
        self._clients: dict = {}
        self.route_counts = {ep: 0 for ep in eps}
        # (endpoint, job_id) -> (input, k, tenant, job_fields, trace)
        # — what failover needs to re-place the job on a surviving
        # replica (the trace context is REUSED: a failover resubmit is
        # the same logical request, ISSUE 18). Keyed by BOTH because
        # daemon job ids are per-process counters: two replicas
        # routinely mint the same "j1".
        self._jobs: dict = {}
        # routing-scrape TTL cache (ISSUE 18): a burst of submits
        # within the TTL reuses one /metrics round-trip per replica
        # instead of paying N; load keys go stale by at most the TTL,
        # which headroom routing tolerates (admission re-checks)
        try:
            self.scrape_ttl_s = float(
                os.environ.get("SHEEP_FLEET_SCRAPE_TTL_S", "1.0"))
        except ValueError:
            self.scrape_ttl_s = 1.0
        self._load_cache: dict = {}  # ep -> (monotonic ts, load key)
        # job_id -> endpoint pins for the resident verbs (ISSUE 17):
        # resident state is replica-local, so update/epoch/compact
        # must keep hitting the owning replica and NEVER fail over
        self._resident: dict = {}

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _client(self, ep: str) -> SheepClient:
        c = self._clients.get(ep)
        if c is None:
            c = SheepClient(ep, timeout_s=self.timeout_s,
                            reconnect=self.reconnect,
                            reconnect_base_s=self._reconnect_base_s)
            self._clients[ep] = c
        return c

    def _down(self, ep: str) -> bool:
        """Distinguish a dead replica from a daemon that answered an
        error: a live one still pings."""
        try:
            self._client(ep).ping()
            return False
        except (ServerError, OSError, json.JSONDecodeError):
            return True

    def _lookup_round(self, digest: str):
        """One lookup sweep: (live_endpoints, first_hit_endpoint)."""
        live, hit = [], None
        for ep in self.endpoints:
            try:
                r = self._client(ep).request({"op": "lookup",
                                              "digest": digest})
                live.append(ep)
                if hit is None and r.get("hit"):
                    hit = ep
            except ServerError:
                # the daemon answered (maybe a pre-fleet one without
                # the lookup verb): live, treated as a miss
                live.append(ep)
            except (OSError, json.JSONDecodeError):
                pass
        return live, hit

    def _load(self, ep: str):
        """(queued+active, -headroom) load key; None if unreachable.
        Answers from the TTL cache within ``scrape_ttl_s`` of the last
        scrape (ISSUE 18); each real scrape's wall cost lands on the
        ``fleet_scrape_ms`` obs counter, cache answers on
        ``fleet_scrape_cache_hits``."""
        from sheep_tpu import obs

        cached = self._load_cache.get(ep)
        if cached is not None \
                and time.monotonic() - cached[0] < self.scrape_ttl_s:
            obs.inc("fleet_scrape_cache_hits")
            return cached[1]
        t0 = time.perf_counter()
        try:
            text = self._client(ep).metrics()
        except (ServerError, OSError, json.JSONDecodeError):
            self._load_cache[ep] = (time.monotonic(), None)
            return None
        obs.inc("fleet_scrape_ms",
                round((time.perf_counter() - t0) * 1000.0, 3))
        from sheep_tpu.obs.metrics import parse_prometheus

        gauges = parse_prometheus(text)

        def one(name, default):
            rows = gauges.get(name) or []
            return float(rows[0][1]) if rows else default

        depth = one("sheepd_queue_depth", 0.0) \
            + one("sheepd_active_jobs", 0.0)
        headroom = one("sheepd_headroom_bytes", float("inf"))
        key = (depth, -headroom)
        self._load_cache[ep] = (time.monotonic(), key)
        return key

    def _route(self, live):
        scored = []
        for i, ep in enumerate(live):
            load = self._load(ep)
            if load is not None:
                scored.append((load, i, ep))
        if not scored:
            return live[0] if live else None
        scored.sort()
        return scored[0][2]

    def _submit_to(self, ep: str, why: str, digest: str, input: str,
                   k, tenant: str, job_fields: dict,
                   reattach: bool = False,
                   trace: Optional[str] = None) -> dict:
        from sheep_tpu import obs

        resp = self._client(ep).submit(input, k=k, tenant=tenant,
                                       reattach=reattach, trace=trace,
                                       **job_fields)
        self.route_counts[ep] = self.route_counts.get(ep, 0) + 1
        jid = resp.get("job_id")
        if jid:
            self._jobs[(ep, jid)] = (input, k, tenant,
                                     dict(job_fields), trace)
        obs.event("fleet_route", endpoint=ep, why=why, digest=digest,
                  job_id=jid, trace=_trace_id_of(trace),
                  counts=dict(self.route_counts))
        resp["endpoint"] = ep
        return resp

    def submit(self, input: str, k, tenant: str = "default",
               reattach: bool = False, **job_fields) -> dict:
        """Route one submit per the class policy. ``reattach`` is
        accepted for :class:`SheepClient` signature compatibility but
        ignored: first submits are plain (a repeat digest must reach
        the result store, not reattach to a retained terminal twin);
        failover resubmission adds ``reattach=True`` itself.

        One trace id is minted per LOGICAL request (ISSUE 18): the
        client-side ``fleet_request`` span carries it, the wire
        ``trace`` field propagates it to whichever replica takes the
        job, and a later failover resubmit reuses it — so the client
        route span and every replica's job span stitch into one tree
        (``trace_report --stitch``)."""
        del reattach
        from sheep_tpu import obs

        digest = fleet_digest(input, k, tenant=tenant, **job_fields)
        tid = protocol.mint_trace_id()
        sp = obs.begin_detached("fleet_request", trace=tid,
                                digest=digest, tenant=str(tenant))
        tp = protocol.make_traceparent(tid, getattr(sp, "id", None))
        tried: set = set()
        try:
            while True:
                live, hit = self._lookup_round(digest)
                live = [e for e in live if e not in tried]
                if hit is not None and hit not in tried:
                    ep, why = hit, "cache_hit"
                else:
                    ep, why = self._route(live), "headroom"
                if ep is None:
                    raise ServerError("no live endpoint among "
                                      + ",".join(self.endpoints))
                try:
                    resp = self._submit_to(ep, why, digest, input, k,
                                           tenant, dict(job_fields),
                                           trace=tp)
                    sp.annotate(endpoint=ep, why=why,
                                job_id=resp.get("job_id"))
                    return resp
                except (OSError, json.JSONDecodeError):
                    # died between lookup and submit: strike, reroute
                    tried.add(ep)
        finally:
            sp.end()

    def _resolve(self, job):
        """(endpoint, job_id) key for a job handle.

        The handle is either a submit/status DESCRIPTOR (preferred —
        its ``endpoint`` + ``job_id`` pin the replica) or a bare job
        id, honored only while unambiguous: daemon job ids are
        per-process counters, so two replicas routinely mint the same
        ``j1``, and guessing between them could answer a wait with a
        DIFFERENT tenant's job."""
        if isinstance(job, dict):
            ep, jid = job.get("endpoint"), job.get("job_id")
            if ep is not None and (ep, jid) in self._jobs:
                return ep, jid
            job = jid
        matches = [key for key in self._jobs if key[1] == job]
        if len(matches) == 1:
            return matches[0]
        if not matches:
            raise ServerError(f"unknown fleet job {job}")
        raise ServerError(
            f"job id {job} is ambiguous across replicas "
            f"({', '.join(ep for ep, _ in matches)}) — pass the "
            f"submit descriptor (it carries the endpoint) instead "
            f"of the bare id")

    def _failover(self, key, exc) -> dict:
        """The job's home replica is gone: re-place it on a survivor
        (reattach-idempotent) and return the NEW descriptor. The
        resubmit REUSES the logical request's trace context, and the
        client-side ``fleet_failover`` span nests under the original
        ``fleet_request`` span — the failover seam is one visible
        edge in the stitched tree (ISSUE 18)."""
        from sheep_tpu import obs

        home, job_id = key
        sub = self._jobs.get(key)
        if sub is None:
            raise exc
        self._jobs.pop(key, None)
        input, k, tenant, job_fields, tp = sub
        digest = fleet_digest(input, k, tenant=tenant, **job_fields)
        tid = parent = None
        if tp:
            try:
                tid, phex = protocol.parse_traceparent(tp)
                parent = int(phex, 16) if phex else None
            except protocol.ProtocolError:
                pass
        sp = obs.begin_detached("fleet_failover", parent=parent,
                                trace=tid, from_endpoint=home,
                                from_job=job_id)
        try:
            for ep in self.endpoints:
                if ep == home or self._down(ep):
                    continue
                try:
                    resp = self._submit_to(ep, "failover", digest,
                                           input, k, tenant,
                                           job_fields, reattach=True,
                                           trace=tp)
                    sp.annotate(endpoint=ep,
                                job_id=resp.get("job_id"))
                    return resp
                except (ServerError, OSError, json.JSONDecodeError):
                    continue
            raise ServerError(
                f"job {job_id}: home replica {home} died and no live "
                f"replica accepted the failover resubmit") from exc
        finally:
            sp.end()

    def status(self, job) -> dict:
        """Job descriptor, following failover: when the home replica
        died the job is re-placed and the returned descriptor carries
        the NEW job_id/endpoint — poll loops should track the
        descriptor, not the bare id."""
        while True:
            ep, jid = self._resolve(job)
            try:
                return self._client(ep).status(jid)
            except (ServerError, OSError,
                    json.JSONDecodeError) as e:
                if isinstance(e, ServerError) and not self._down(ep):
                    raise
                job = self._failover((ep, jid), e)

    def wait(self, job, timeout_s: Optional[float] = None) -> dict:
        """Block until terminal, following failover like
        :meth:`status` (the returned descriptor is authoritative)."""
        while True:
            ep, jid = self._resolve(job)
            try:
                return self._client(ep).wait(jid, timeout_s)
            except (ServerError, OSError,
                    json.JSONDecodeError) as e:
                if isinstance(e, ServerError) and not self._down(ep):
                    raise
                job = self._failover((ep, jid), e)

    def result_assignment(self, job: dict, k: Optional[int] = None):
        return SheepClient.result_assignment(self, job, k)

    # -- resident-partition verbs across the fleet (ISSUE 17) ----------
    def _locate_resident(self, job) -> "tuple":
        """Pin the replica owning a resident job.

        The handle is a submit descriptor (its ``endpoint`` pins
        directly) or a bare id, resolved by sweeping every replica's
        ``status`` — exactly one owner pins it, zero or several is an
        error. Unlike the submit family these verbs NEVER fail over:
        the resident table lives in the owning replica's memory and
        state dir, so another replica cannot answer for it."""
        if isinstance(job, dict):
            ep, jid = job.get("endpoint"), job.get("job_id")
            if ep is not None and jid is not None:
                self._resident[jid] = ep
                return ep, jid
            job = jid
        job_id = str(job)
        ep = self._resident.get(job_id)
        if ep is not None:
            return ep, job_id
        owners = []
        for cand in self.endpoints:
            try:
                self._client(cand).status(job_id)
                owners.append(cand)
            except ServerError:
                continue  # live replica, doesn't know the job
            except (OSError, json.JSONDecodeError):
                continue  # dead replica: nothing servable there
        if not owners:
            raise ServerError(
                f"no live replica knows job {job_id!r} (swept "
                f"{','.join(self.endpoints)}); resident partitions "
                f"are replica-local — if the owning replica died, "
                f"restart it (durable daemons resume residents) or "
                f"resubmit --resident elsewhere")
        if len(owners) > 1:
            raise ServerError(
                f"job id {job_id!r} is ambiguous across replicas "
                f"({', '.join(owners)}) — daemon job ids are "
                f"per-process counters; pass the submit descriptor "
                f"(it carries the endpoint) instead of the bare id")
        self._resident[job_id] = owners[0]
        return owners[0], job_id

    def _resident_call(self, job, fn):
        ep, job_id = self._locate_resident(job)
        try:
            return fn(self._client(ep), job_id)
        except (OSError, json.JSONDecodeError) as e:
            self._resident.pop(job_id, None)
            raise ServerError(
                f"replica {ep} owning resident job {job_id} went "
                f"away mid-request ({e}); resident state is "
                f"replica-local so this verb cannot fail over — "
                f"restart that replica (a durable daemon resumes its "
                f"resident partitions at their last epoch) and "
                f"retry") from e

    def update(self, job, adds=None, dels=None,
               epoch: Optional[int] = None, score: bool = False,
               compact: str = "auto", log: Optional[str] = None,
               chunk_edges: Optional[int] = None) -> dict:
        """Apply a delta epoch to a resident job's OWNING replica
        (pinned; see :meth:`_locate_resident`). Signature and chunked
        streaming as :meth:`SheepClient.update`."""
        return self._resident_call(
            job, lambda c, jid: c.update(
                jid, adds=adds, dels=dels, epoch=epoch, score=score,
                compact=compact, log=log, chunk_edges=chunk_edges))

    def epoch(self, job) -> dict:
        return self._resident_call(
            job, lambda c, jid: c.epoch(jid))

    def compact(self, job, mode: str = "auto",
                score: bool = False) -> dict:
        return self._resident_call(
            job, lambda c, jid: c.compact(jid, mode=mode,
                                          score=score))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sheep-submit",
        description="submit partition jobs to a running sheepd")
    p.add_argument("--server",
                   help="daemon address: unix socket path or host:port")
    p.add_argument("--endpoints", metavar="A,B,...", default=None,
                   help="fleet mode: comma list of replica addresses; "
                        "submits route to a result-cache digest hit "
                        "first, else the least-loaded replica, with "
                        "failover resubmission if a replica dies. "
                        "Resident verbs (--update/--epoch-of/"
                        "--compact) route to the replica OWNING the "
                        "job and never fail over; other admin verbs "
                        "use --server")
    p.add_argument("--input", help="graph path or synthetic spec "
                                   "(as the main CLI's --input)")
    p.add_argument("--k", help="part count, or comma list for multi-k "
                               "from one shared tree")
    p.add_argument("--tenant", default="default")
    p.add_argument("--chunk-edges", type=int, default=None)
    p.add_argument("--dispatch-batch", type=int, default=None)
    p.add_argument("--h2d-ring", type=int, default=None,
                   help="staged H2D ring depth for host-format inputs "
                        "(0 = auto; device-generated specs skip "
                        "staging)")
    p.add_argument("--inflight", type=int, default=None,
                   help="in-job dispatch pipeline depth: confirmed "
                        "executions in flight per engine step (0 = "
                        "auto: 1 on cpu, 2 on accelerators)")
    p.add_argument("--alpha", type=float, default=None)
    p.add_argument("--weights", choices=["unit", "degree"], default=None)
    p.add_argument("--comm-volume", action="store_true")
    p.add_argument("--num-vertices", type=int, default=None)
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="seconds from submit until the job must be "
                        "done (expired -> deadline_exceeded)")
    p.add_argument("--output", default=None,
                   help="daemon-side partition map path (.parts/.pbin)")
    p.add_argument("--wait", action="store_true",
                   help="block until the job is terminal; print its "
                        "descriptor; exit 0 only on done")
    p.add_argument("--watch", action="store_true",
                   help="like --wait but poll status and render live "
                        "progress lines (state/phase/steps) on stderr "
                        "instead of blocking silently")
    p.add_argument("--poll", type=float, default=0.5, metavar="S",
                   help="with --watch: poll interval (default 0.5s)")
    p.add_argument("--reconnect", type=int, default=None, metavar="N",
                   help="survive a daemon bounce: retry transport "
                        "errors up to N times with exponential "
                        "backoff, re-sending idempotent requests "
                        "(submits reattach to the journaled job by "
                        "digest instead of double-building). Default: "
                        "8 with --watch, else 0")
    p.add_argument("--timeout", type=float, default=None,
                   help="with --wait/--watch: give up after this many "
                        "seconds")
    p.add_argument("--resident", action="store_true",
                   help="with --input: hold the finished partition "
                        "RESIDENT in the daemon so delta epochs can "
                        "stream at it (--update); the admission "
                        "reservation stays charged until --cancel "
                        "releases it")
    p.add_argument("--update", metavar="JOB", default=None,
                   help="apply delta epochs to a resident partition; "
                        "needs --deltas LOG (daemon-side path by "
                        "default, --wire streams each epoch inline)")
    p.add_argument("--deltas", metavar="LOG", default=None,
                   help="with --update: the delta log "
                        "(io/deltalog.py) whose epochs past the "
                        "resident epoch apply")
    p.add_argument("--wire", action="store_true",
                   help="with --update: read the log CLIENT-side and "
                        "stream each epoch as an inline update "
                        "request (the remote-tenant path; default "
                        "sends the daemon-side log path)")
    p.add_argument("--score", action="store_true",
                   help="with --update/--compact: refresh + return "
                        "the scored results after applying")
    p.add_argument("--epoch-of", metavar="JOB", default=None,
                   help="print a resident partition's epoch/staleness "
                        "descriptor")
    p.add_argument("--compact", metavar="JOB", default=None,
                   help="compact a resident partition's tombstones")
    p.add_argument("--compact-mode", default="auto",
                   choices=["auto", "full", "subtree", "rebase"],
                   help="with --compact: full re-anchors and rebuilds "
                        "everything (exact), subtree repairs only the "
                        "dirty tree-split parts (score-bounded), "
                        "rebase additionally rewrites base+deltas "
                        "into a fresh on-disk artifact (durable "
                        "daemons only; explicit opt-in), auto picks "
                        "between full/subtree (default)")
    p.add_argument("--status", metavar="JOB")
    p.add_argument("--cancel", metavar="JOB")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--ping", action="store_true")
    p.add_argument("--metrics", action="store_true",
                   help="print the daemon's live Prometheus text")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="arm an on-demand jax.profiler capture into "
                        "daemon-side DIR")
    p.add_argument("--profile-steps", type=int, default=8, metavar="K",
                   help="with --profile: capture the next K dispatch "
                        "steps (default 8)")
    p.add_argument("--shutdown", action="store_true")
    p.add_argument("--drain", action="store_true",
                   help="with --shutdown: finish accepted jobs first")
    return p


def _watch_job(c: "SheepClient", job, poll_s: float,
               timeout_s: Optional[float]) -> dict:
    """Poll status until terminal (or timeout), rendering one progress
    line per change on stderr; returns the last descriptor. ``job``
    is a bare id (SheepClient) or the submit descriptor (FleetClient
    — replica job ids collide, the descriptor pins the endpoint).
    Daemon bounces are absorbed below in ``request`` when the client
    was built with ``reconnect`` (the --watch default): each poll
    retries transports with backoff, so a restarting daemon shows up
    as a few stderr retry notes and then the resumed job's progress —
    not a dead watch."""
    t0 = time.monotonic()
    deadline = None if timeout_s is None else t0 + timeout_s
    last_line = None
    while True:
        desc = c.status(job)
        # fleet failover re-places a job on a surviving replica under
        # a NEW id; the descriptor's job_id is authoritative
        job = desc.get("job_id") or job
        job_id = job if isinstance(job, str) else job.get("job_id")
        state = desc.get("state")
        bits = [f"{time.monotonic() - t0:7.1f}s", job_id, state]
        if desc.get("phase"):
            bits.append(f"phase={desc['phase']}")
        if desc.get("steps"):
            bits.append(f"steps={desc['steps']}")
        if state == "done" and desc.get("results"):
            r = desc["results"][0]
            bits.append(f"cut_ratio={r.get('cut_ratio')}")
        if desc.get("error"):
            bits.append(f"error={desc['error'][:120]}")
        line = " ".join(bits)
        if line != last_line:
            print(f"sheep-submit: {line}", file=sys.stderr, flush=True)
            last_line = line
        if state in protocol.TERMINAL_STATES:
            return desc
        if deadline is not None and time.monotonic() >= deadline:
            return desc
        time.sleep(max(0.05, poll_s))


def main(argv=None) -> int:
    p = build_parser()
    args = p.parse_args(argv)
    modes = [bool(args.input), bool(args.status), bool(args.cancel),
             args.stats, args.ping, args.shutdown, args.metrics,
             bool(args.profile), bool(args.update),
             bool(args.epoch_of), bool(args.compact)]
    if sum(modes) != 1:
        p.error("pass exactly one of --input (submit), --status, "
                "--cancel, --stats, --ping, --metrics, --profile, "
                "--update, --epoch-of, --compact, --shutdown")
    if bool(args.server) == bool(args.endpoints):
        p.error("pass exactly one of --server or --endpoints")
    if args.endpoints and not (args.input or args.update
                               or args.epoch_of or args.compact):
        p.error("--endpoints (fleet mode) covers submits and the "
                "resident verbs (--update/--epoch-of/--compact, "
                "routed to the replica owning the job); point "
                "--server at one replica for other admin verbs")
    if args.update and not args.deltas:
        p.error("--update needs --deltas LOG")
    reconnect = args.reconnect if args.reconnect is not None \
        else (8 if args.watch else 0)
    if reconnect < 0:
        p.error("--reconnect must be >= 0")
    try:
        if args.endpoints:
            client = FleetClient(args.endpoints, reconnect=reconnect)
        else:
            client = SheepClient(args.server, reconnect=reconnect)
        with client as c:
            if args.ping:
                print(json.dumps(c.ping()))
                return 0
            if args.stats:
                print(json.dumps(c.stats(), indent=1))
                return 0
            if args.metrics:
                sys.stdout.write(c.metrics())
                return 0
            if args.profile:
                print(json.dumps(c.profile(args.profile,
                                           steps=args.profile_steps)))
                return 0
            if args.shutdown:
                print(json.dumps(c.shutdown(drain=args.drain)))
                return 0
            if args.epoch_of:
                print(json.dumps(c.epoch(args.epoch_of)))
                return 0
            if args.compact:
                print(json.dumps(c.compact(args.compact,
                                           mode=args.compact_mode,
                                           score=args.score)))
                return 0
            if args.update:
                if args.wire:
                    # remote-tenant path: read the log HERE, stream
                    # each epoch inline (idempotent: explicit epoch
                    # numbers — an already-applied epoch is a no-op)
                    from sheep_tpu.io.deltalog import DeltaLogReader

                    cur = int(c.epoch(args.update)["epoch"])
                    resp = {"job_id": args.update, "epoch": cur,
                            "applied": False, "epochs_applied": 0}
                    applied = 0
                    reader = DeltaLogReader(args.deltas)
                    mx = reader.max_epoch  # records() cached: 1 read
                    for ep, adds, dels in reader.epochs(
                            start_epoch=cur):
                        resp = c.update(args.update, adds=adds,
                                        dels=dels, epoch=ep,
                                        score=args.score and ep == mx)
                        applied += resp.get("epochs_applied", 0)
                    resp["epochs_applied"] = applied
                    resp["applied"] = applied > 0
                else:
                    resp = c.update(args.update, log=args.deltas,
                                    score=args.score)
                print(json.dumps(resp))
                return 0
            if args.status:
                print(json.dumps(c.status(args.status)))
                return 0
            if args.cancel:
                print(json.dumps({"job_id": args.cancel,
                                  "state": c.cancel(args.cancel)}))
                return 0
            # submit
            if not args.k:
                p.error("--input needs --k")
            try:
                ks = [int(x) for x in str(args.k).split(",") if x != ""]
            except ValueError:
                ks = []
            if not ks or any(k < 1 for k in ks):
                p.error(f"--k must be a positive int or comma list "
                        f"(got {args.k!r})")
            job = {"k": ks}
            for field, val in (("chunk_edges", args.chunk_edges),
                               ("dispatch_batch", args.dispatch_batch),
                               ("h2d_ring", args.h2d_ring),
                               ("inflight", args.inflight),
                               ("alpha", args.alpha),
                               ("weights", args.weights),
                               ("num_vertices", args.num_vertices),
                               ("deadline_s", args.deadline),
                               ("output", args.output)):
                if val is not None:
                    job[field] = val
            if args.comm_volume:
                job["comm_volume"] = True
            if args.resident:
                job["resident"] = True
            # with failover armed the submit itself must be idempotent
            # (the retried submit against a restarted daemon reattaches
            # to the journaled job instead of double-building)
            resp = c.submit(args.input, tenant=args.tenant,
                            reattach=reconnect > 0, **job)
            if not (args.wait or args.watch):
                print(json.dumps(resp))
                return 0
            # fleet handles are the full descriptor (replica job ids
            # collide across daemons; the endpoint disambiguates)
            handle = resp if args.endpoints else resp["job_id"]
            if args.watch:
                desc = _watch_job(c, handle, args.poll, args.timeout)
            else:
                desc = c.wait(handle, timeout_s=args.timeout)
            print(json.dumps(desc))
            if desc.get("state") == "done":
                return 0
            if desc.get("state") in ("queued", "running"):
                # --timeout elapsed with the job still in flight: NOT a
                # terminal failure — a supervisor must not resubmit
                print(f"sheep-submit: wait timed out; job "
                      f"{desc.get('job_id')} is still "
                      f"{desc.get('state')}", file=sys.stderr)
                return 4
            return 3
    except (ServerError, OSError, json.JSONDecodeError) as e:
        kind = "daemon" if isinstance(e, ServerError) else "transport"
        print(f"sheep-submit: {kind} error: {e}", file=sys.stderr)
        return 2 if isinstance(e, ServerError) else 1


if __name__ == "__main__":
    sys.exit(main())
