"""sheep_tpu.server — partition-as-a-service (ISSUE 10 tentpole).

Every CLI run is a cold process that pays 8-13 s of jit warm-up before
touching an edge (BENCH_r03-r05 ``warm-up`` lines); this package makes
the partitioner a long-lived RESOURCE instead of a batch process:

- :mod:`~sheep_tpu.server.daemon` — ``sheepd``, a resident daemon
  holding the compiled fixpoint/split/score programs (jax jit caches
  are per-process, so a warm daemon recompiles nothing for repeat
  shapes), the device chunk cache, and a membudget-aware admission
  scheduler, serving partition requests over a local unix-socket/TCP
  JSON API;
- :mod:`~sheep_tpu.server.scheduler` — the multi-tenant job queue +
  the dispatch loop that INTERLEAVES staged segments from different
  jobs on one dispatch chain (sound: each job's elimination fixpoint
  is order-independent in its own constraint multiset — the PR-1/PR-3
  invariant, applied across jobs);
- :mod:`~sheep_tpu.server.engine` — one job as a cooperative step
  generator over the existing ops (degrees/sort/build/split/score),
  with per-job fault degradation and per-job obs span trees;
- :mod:`~sheep_tpu.server.protocol` — the JSON wire protocol (request/
  response schema, job states, assignment codec);
- :mod:`~sheep_tpu.server.client` — the thin client +
  ``sheep-submit`` CLI (``--watch`` renders live per-job progress;
  ``--reconnect`` rides out daemon bounces with idempotent reattach
  submits);
- :mod:`~sheep_tpu.server.journal` — the crash-safe job journal
  (ISSUE 14): an append-only line-JSON WAL that makes a
  ``--state-dir`` daemon restart-survivable — queued jobs re-admit,
  running jobs resume from per-job checkpoints bit-identically, and
  SIGTERM becomes a graceful checkpoint-and-drain;
- :mod:`~sheep_tpu.server.sheeptop` — ``sheeptop``, the live console
  view over the ``metrics`` + ``list`` verbs (ISSUE 11).

Live telemetry (ISSUE 11): the scheduler owns a typed
:class:`~sheep_tpu.obs.metrics.MetricRegistry` (per-tenant
request-latency histograms, queue/reservation gauges, admission and
retry counters) answered by the ``metrics`` verb and the daemon's
optional HTTP ``GET /metrics`` listener (``--metrics-port``); an
always-on bounded flight recorder dumps each failed job's last events
to the trace sink; and the ``profile`` verb arms an on-demand
``jax.profiler`` capture of the next K dispatch steps.

Served results are bit-identical to the cold CLI build of the same
input: the forest is the unique fixpoint of the stream's constraint
multiset, the split/score passes are deterministic in it, and the
engine reuses the very ops the backends run (tests/test_server.py
pins single-job and interleaved-job bit-equality).
"""

from sheep_tpu.server.protocol import JOB_STATES, JobSpec  # noqa: F401


def __getattr__(name):
    # Scheduler pulls in the engine (and with it jax + the backends);
    # keep that import lazy so the thin client / sheep-submit stays a
    # sockets+json tool that works without an accelerator stack
    if name == "Scheduler":
        from sheep_tpu.server.scheduler import Scheduler

        return Scheduler
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
