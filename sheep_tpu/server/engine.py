"""One served partition job as a cooperative step generator.

The daemon cannot afford one thread blocked per job (a blocked host
thread serializes nothing usefully — device executions already
serialize on the one dispatch chain), so a job is a GENERATOR over the
existing ops: each ``yield`` marks one unit of device work done
(a degrees chunk, a staged build group, a scoring chunk), and the
scheduler round-robins ``next()`` across admitted jobs. That makes the
interleave explicit and deterministic: staged segments from DIFFERENT
jobs alternate on one dispatch chain, each folding into its own
carried table — sound because each job's elimination fixpoint is
order-independent in its own constraint multiset (the PR-1/PR-3
invariant; no job ever reads another's table).

Bit-identity with the cold CLI build is by construction, not by luck:
the degree accumulation (int64 host totals), the rank clip, the
elimination order, the batched fold (unique fixpoint at any batch
shape), the host tree split and the scoring pass are the same ops the
``tpu`` backend drives, in the same vertex spaces.

Fault containment (per job, ISSUE 9 reused): each staged group folds
under the job's own :class:`~sheep_tpu.utils.retry.RetryPolicy` —
an OOM-class fault degrades THAT job's dispatch batch (membudget
model) and re-folds the same staged block bit-identically
(``donate=False`` keeps the inputs valid across the retry); read
faults never even surface here (the edgestream's bounded retry
absorbs them). A fault that exhausts its budget fails the job, not
the daemon.

Cancellation: the scheduler calls ``close()`` on the step generator;
GeneratorExit unwinds through the ``finally`` blocks below, which
close the chunk/group iterators — and through them the prefetch
workers (``Prefetcher.close()``: stop + drain + join) — and end the
job's phase spans, deterministically, before the job is marked
cancelled.

Durability (ISSUE 14): a durable scheduler hands each job a per-job
:class:`~sheep_tpu.utils.checkpoint.Checkpointer` domain (a
subdirectory of the daemon's checkpoint dir keyed by job id). The
engine saves at chunk/group boundaries on the checkpointer's cadence
— each save pulls the carried table to host, which IS the PR-3 flush
barrier (the pulled state is confirmed, nothing in flight can
under-represent it) — and on (re)start resumes from the newest intact
step: degrees resume restores the int64 host totals (exact integer
addition, so early flushes at save points change nothing), build
resume restores the carried table and re-folds the remaining chunks
into it (bit-identical: the same folds in the same order), score
resume restores the per-k counters and the host forest. A resumed
served forest is therefore bit-identical to the uninterrupted served
build, which is itself bit-identical to the cold CLI build.
:meth:`request_checkpoint` arms an off-cadence save at the next
boundary — the graceful-drain hook (``sheepd`` SIGTERM): once the
save lands, ``suspend_ready`` flips and the scheduler parks the job.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

import jax.numpy as jnp

from sheep_tpu import obs
from sheep_tpu.backends.tpu_backend import (_device_chunk_groups,
                                            _device_chunks,
                                            resolve_dispatch_batch,
                                            resolve_h2d_ring,
                                            resolve_inflight)
from sheep_tpu.io.devicestream import is_device_stream
from sheep_tpu.io.edgestream import open_input
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.ops import score as score_ops
from sheep_tpu.ops import split as split_ops
from sheep_tpu.types import PartitionResult, check_tpu_vertex_range
from sheep_tpu.utils import checkpoint as ckpt_mod
from sheep_tpu.utils import retry as retry_mod


class JobEngine:
    """Drives one admitted job; see module docstring. ``job`` is a
    :class:`sheep_tpu.server.scheduler.Job`; ``cache`` an optional
    shared device chunk cache (the daemon's, keyed to this input);
    ``checkpointer`` an optional per-job recovery domain, with
    ``resume`` asking for a resume from its newest intact step."""

    def __init__(self, job, cache=None, checkpointer=None,
                 resume: bool = False):
        self.job = job
        self.cache = cache
        self.ckpt = checkpointer
        self.resume = bool(resume)
        # graceful-drain handshake: request_checkpoint() arms an
        # off-cadence save at the next boundary; the save flips
        # suspend_ready and the scheduler parks the job (benign
        # cross-thread bool — armed under the scheduler lock, read by
        # the dispatch thread between steps)
        self._ckpt_request = False
        self.suspend_ready = False
        # live dispatch knobs — the retry layer's degrade hook halves
        # these mid-build; the staging loop restages at the new shape
        self.batch: Optional[int] = None
        self.ring: int = 1
        self._n = 0
        self._cs = 0
        self._build_idx = 0
        self._dev_stream = False

    # -- durability hooks (ISSUE 14) -----------------------------------
    def request_checkpoint(self) -> None:
        """Arm a save at the next chunk/group boundary regardless of
        cadence — the scheduler's graceful-drain hook."""
        if self.ckpt is not None:
            self._ckpt_request = True
        else:
            self.suspend_ready = True  # nothing to save; park now

    def _save(self, phase: str, idx: int, arrays: dict, meta) -> None:
        self.ckpt.save(phase, int(idx), arrays, meta)
        stats = self.job.stats
        stats["ckpt_saves"] = stats.get("ckpt_saves", 0) + 1
        if self._ckpt_request:
            self._ckpt_request = False
            self.suspend_ready = True

    def _save_score(self, idx: int, minp_host, deg_host, cut: dict,
                    total: int, cv_chunks: dict, rounds: int,
                    meta) -> None:
        """Score-phase save: per-k cut counters + the host forest; the
        cv-key accumulators are compacted into the checkpoint and
        carried forward compacted (the save_score_state convention)."""
        arrays = {"minp": np.asarray(minp_host),
                  "deg": np.asarray(deg_host),
                  "total": np.int64(total), "rounds": np.int64(rounds)}
        for k, c in cut.items():
            arrays[f"cut_k{k}"] = np.int64(c)
            if self.job.spec.comm_volume:
                keys = ckpt_mod.compact_cv_keys(cv_chunks[k])
                arrays[f"cv_k{k}"] = keys
                cv_chunks[k] = [keys]
        self._save("score", idx, arrays, meta)

    # -- fault hooks (per job; the daemon survives, the job degrades) --
    def _on_resource(self):
        # DETACH from the shared chunk cache rather than clearing it in
        # place: a suspended _device_chunks iterator may be mid-way
        # through cache.chunks, and emptying the list under it would
        # make it restart the upload stream at 0 (re-folding the prefix
        # — harmless for the fixpoint, but wasted device work and a
        # skewed step count). The cache_shed flag tells the scheduler
        # to drop the whole entry at finalize, so the HBM is released
        # when the engine's references die and future jobs start fresh.
        if self.cache is not None:
            self.cache = None
            self.job.cache_shed = True
        nxt = retry_mod.degrade_dispatch(
            self._n, self._cs, self.batch or 1, 1, False,
            self.job.stats, self._build_idx,
            h2d_ring=None if self._dev_stream else self.ring)
        if nxt is not None:
            self.batch = nxt[0]
            if len(nxt) > 2:
                self.ring = nxt[2]

    def _enter_phase(self, phase: str) -> None:
        # live progress signal (ISSUE 11): the job descriptor's phase
        # field updates at phase ENTRY (the scheduler confirms it from
        # each step's yield value afterward), and the transition lands
        # in the trace + the job's flight-recorder ring
        self.job.phase = phase
        obs.event("job_phase", job=self.job.id, phase=phase)

    def _phase_span(self, name: str):
        # phase spans parent locally to the job span; a propagated
        # trace id (ISSUE 18) rides on each so --stitch can collect a
        # job's whole subtree by trace attr even across files
        tid = getattr(self.job, "trace_id", None)
        return obs.begin_detached(
            name, parent=self.job.span_id,
            **({"trace": tid} if tid else {}))

    def _on_device_loss(self):
        # best-effort in-process runtime reinit (utils/retry, ISSUE 9):
        # THIS job's live device arrays died with the old client, so
        # its own retries usually exhaust and the job FAILS — but the
        # reinit is what keeps the resident daemon able to serve the
        # NEXT job on a fresh runtime instead of failing every request
        # against a dead accelerator forever. (A durable daemon then
        # also resumes the lossy job from its last checkpoint on
        # restart — the served kill+resume contract, ISSUE 14.)
        retry_mod.recover_device_loss(self.job.stats, self._build_idx)

    def steps(self):
        """The step generator (see module docstring); sets
        ``job.results`` before finishing."""
        job = self.job
        spec = job.spec
        stats = job.stats
        stats_acc = obs.stats_accumulator()
        policy = retry_mod.RetryPolicy()
        t_phase: dict = {}
        with open_input(spec.input,
                        n_vertices=spec.num_vertices) as es:
            n = es.num_vertices
            check_tpu_vertex_range(n, "sheepd")
            cs = es.clamp_chunk_edges(spec.chunk_edges)
            self._n, self._cs = n, cs
            # staged H2D ring (ISSUE 12): device-stream inputs
            # (rmat-hash:/sbm-hash: specs) synthesize chunks in
            # accelerator memory — zero host bytes per served chunk;
            # host-format inputs stage through the ring exactly as the
            # CLI's tpu driver does (same _device_chunks supplier).
            # The ring resolves BEFORE the batch so the auto batch
            # sizing reserves the staged blocks in the HBM model (the
            # tpu backend's ring_model rule)
            self._dev_stream = is_device_stream(es)
            self.ring = resolve_h2d_ring(spec.h2d_ring)
            # in-job pipeline depth (ISSUE 16): D issued executions'
            # staging blocks live at once — resolved BEFORE the batch
            # so auto sizing reserves them in the HBM model
            depth = resolve_inflight(spec.inflight)
            self.batch = resolve_dispatch_batch(
                spec.dispatch_batch, n, cs, inflight=depth,
                h2d_ring=0 if self._dev_stream else self.ring)
            stats["dispatch_batch"] = self.batch
            stats["inflight_depth"] = depth
            job.n_vertices = n

            # ---- durable resume (ISSUE 14) --------------------------
            meta = None
            state = None
            if self.ckpt is not None:
                # every bit-affecting option is in the fingerprint; a
                # mismatch (input changed under the journaled job)
                # raises and FAILS the job — resuming would corrupt it
                meta = ckpt_mod.stream_meta(
                    es, k=int(spec.ks[0]), chunk_edges=cs,
                    weights=spec.weights, alpha=spec.alpha,
                    comm_volume=spec.comm_volume,
                    ks=[int(k) for k in spec.ks],
                    segment_rounds=int(spec.segment_rounds), served=1)
                state = ckpt_mod.resume_state(self.ckpt, meta,
                                              self.resume)
                if state is not None:
                    stats["resume_phase_idx"] = float(
                        ckpt_mod.phase_index(state.phase))
                    stats["resume_chunk_idx"] = float(state.chunk_idx)
            resume_phase = state.phase if state is not None else None

            # ---- degrees --------------------------------------------
            t0 = time.perf_counter()
            deg_start = 0
            deg_host = np.zeros(n, dtype=np.int64)
            if resume_phase == "degrees":
                deg_host = state.arrays["deg"].astype(np.int64)
                deg_start = int(state.chunk_idx)
            if resume_phase in (None, "degrees"):
                self._enter_phase("degrees")
                sp = self._phase_span("degrees")
                deg = degrees_ops.init_degrees(n)
                flush_every = degrees_ops.flush_every_for(cs)
                since = 0
                idx = deg_start
                chunks = _device_chunks(es, cs, n, self.cache,
                                        deg_start, self.ring, stats)
                try:
                    for padded in chunks:
                        deg = degrees_ops.degree_chunk(deg, padded, n)
                        since += 1
                        idx += 1
                        at_ckpt = self.ckpt is not None and (
                            self.ckpt.due(idx - deg_start)
                            or self._ckpt_request)
                        if since >= flush_every or at_ckpt:
                            # early flushes at save points are exact:
                            # integer degree sums are associative
                            deg_host += np.asarray(  # sheeplint: sync-ok
                                deg[:n], dtype=np.int64)
                            deg = degrees_ops.init_degrees(n)
                            since = 0
                        if at_ckpt:
                            self._save("degrees", idx,
                                       {"deg": deg_host}, meta)
                        stats_acc.absorb(stats)
                        yield "degrees"
                finally:
                    chunks.close()
                    sp.end()
                deg_host += np.asarray(deg[:n],  # sheeplint: sync-ok
                                       dtype=np.int64)
            else:
                # build/score resume: the completed degree totals ride
                # in every later-phase checkpoint
                deg_host = state.arrays["deg"].astype(np.int64)
            t_phase["degrees"] = time.perf_counter() - t0

            # ---- sort (one step; recomputed on resume — the order is
            # a pure deterministic function of the degree totals) -----
            t0 = time.perf_counter()
            self._enter_phase("sort")
            sp = self._phase_span("sort")
            try:
                # the rank clip + flush cadence are SHARED with the tpu
                # backend (ops/degrees.py) — the served==CLI bit-identity
                # contract must not rest on two hand-maintained copies
                deg_rank = degrees_ops.rank_clip_i32(deg_host)
                deg_dev = jnp.asarray(deg_rank, dtype=jnp.int32)
                pos, order = order_ops.elimination_order(deg_dev, n)
                # tiny pull as the real completion barrier (same rule
                # as the tpu backend: block_until_ready is not a
                # barrier on a tunneled device)
                pos_host = np.asarray(pos[:n])  # sheeplint: sync-ok
            finally:
                sp.end()
            t_phase["sort"] = time.perf_counter() - t0
            yield "sort"

            # ---- build: staged batched dispatch ---------------------
            total_rounds = 0
            if resume_phase == "score":
                # build completed before the save; its confirmed forest
                # rides in the score checkpoint
                minp_host = state.arrays["minp"]
                total_rounds = int(state.arrays.get("rounds", 0))
                t_phase["build"] = 0.0
            else:
                t0 = time.perf_counter()
                self._enter_phase("build")
                sp = self._phase_span("build")
                if resume_phase == "build":
                    P = jnp.asarray(state.arrays["p"], dtype=jnp.int32)
                    self._build_idx = int(state.chunk_idx)
                    total_rounds = int(state.arrays.get("rounds", 0))
                else:
                    P = jnp.full(n + 1, n, dtype=jnp.int32)
                    self._build_idx = 0
                sentinel_chunk = None
                # ---- in-job pipelined dispatch (ISSUE 16): compose
                # the PR-3 depth-D pipeline into the served engine.
                # Each fifo entry is one ISSUED but unconfirmed
                # execution — (p_in, loB, hiB, gl, rounds_dev), with
                # p_in the carried table BEFORE that fold
                # (donate=False keeps it and the staged blocks valid).
                # CONFIRMING pulls the rounds scalar — the only
                # per-group host sync; deferring it depth-1 groups
                # lets the host issue ahead of the device and lets
                # interleaved jobs overlap H2D + compute instead of
                # serializing every step on the dispatch thread. The
                # confirmed table after entry i is entry i+1's p_in
                # (the tip when nothing younger is in flight) — what
                # checkpoints save, so a resume re-folds exactly the
                # unconfirmed groups, bit-identically.
                fifo: deque = deque()
                issued_idx = self._build_idx

                def fold_retrying(p, lo, hi):
                    while True:
                        try:
                            # classify/budget/count/backoff on fault —
                            # degrade THIS job, never the daemon;
                            # donate=False keeps p/lo/hi valid for
                            # the retry
                            return elim_ops.fold_segments_batch(
                                p, lo, hi, n,
                                segment_rounds=spec.segment_rounds,
                                stats=stats, donate=False)
                        except Exception as exc:
                            retry_mod.handle_build_fault(
                                policy, exc, f"sheepd.{job.id}.build",
                                stats,
                                on_resource=self._on_resource,
                                on_device_loss=self._on_device_loss)

                def issue(group, gl):
                    nonlocal P
                    loB, hiB = elim_ops.orient_chunks_batch_pos(
                        jnp.stack(group), pos, n)
                    P2, rounds = fold_retrying(P, loB, hiB)
                    fifo.append((P, loB, hiB, gl, rounds))
                    P = P2

                def confirm():
                    # one confirmed execution. A fault surfacing at
                    # the sync (an async failure materializing late)
                    # re-drives every unconfirmed fold synchronously
                    # from the oldest staged inputs — bit-identical:
                    # the same folds in the same order into the same
                    # confirmed table.
                    nonlocal P, total_rounds
                    p_in, loB, hiB, gl, rounds = fifo.popleft()
                    try:
                        r = int(rounds)
                    except Exception as exc:
                        retry_mod.handle_build_fault(
                            policy, exc, f"sheepd.{job.id}.build",
                            stats, on_resource=self._on_resource,
                            on_device_loss=self._on_device_loss)
                        pending = [(p_in, loB, hiB, gl)]
                        pending += [(e[0], e[1], e[2], e[3])
                                    for e in fifo]
                        fifo.clear()
                        P = pending[0][0]
                        r, gl = 0, 0
                        for _p, lo2, hi2, g2 in pending:
                            P2, rr = fold_retrying(P, lo2, hi2)
                            r += int(rr)
                            P = P2
                            gl += g2
                    total_rounds += r
                    prev_idx = self._build_idx
                    self._build_idx += gl
                    if self.ckpt is not None and (
                            self.ckpt.due_span(prev_idx,
                                               self._build_idx)
                            or self._ckpt_request):
                        # the pull IS the flush barrier: the confirmed
                        # table (the next in-flight entry's input, or
                        # the tip with an empty pipe) syncs only
                        # confirmed work, so the saved table can never
                        # over-represent build_idx (PR-3 semantics)
                        p_conf = fifo[0][0] if fifo else P
                        self._save(
                            "build", self._build_idx,
                            {"p": np.asarray(p_conf),  # sheeplint: sync-ok
                             "deg": deg_host,
                             "rounds": np.int64(total_rounds)},
                            meta)

                try:
                    while True:
                        batch = self.batch
                        ring = self.ring
                        groups = _device_chunk_groups(
                            es, cs, n, self.cache, issued_idx,
                            batch, ring, stats)
                        restage = False
                        try:
                            for group in groups:
                                gl = len(group)
                                if gl < batch:
                                    if sentinel_chunk is None:
                                        sentinel_chunk = jnp.full(
                                            (cs, 2), n, jnp.int32)
                                    group = group + [sentinel_chunk] * \
                                        (batch - gl)
                                issue(group, gl)
                                issued_idx += gl
                                if len(fifo) >= depth:
                                    confirm()
                                stats_acc.absorb(stats)
                                yield "build"
                                if self.batch != batch \
                                        or self.ring != ring:
                                    # degraded mid-stream: restage the
                                    # remainder at the new shape (and
                                    # the abandoned supplier's finally
                                    # drains its staged ring blocks);
                                    # in-flight entries stay in the
                                    # pipe and confirm on later steps
                                    restage = True
                                    break
                        finally:
                            groups.close()
                        if not restage:
                            break
                    while fifo:
                        # drain the pipe: a step stays one confirmed
                        # execution, so the tail confirms one per yield
                        confirm()
                        stats_acc.absorb(stats)
                        yield "build"
                finally:
                    sp.end(rounds=int(total_rounds))
                minp = P[pos]
                minp_host = np.asarray(minp)  # barrier  # sheeplint: sync-ok
                t_phase["build"] = time.perf_counter() - t0
            stats["fixpoint_rounds"] = float(total_rounds)

            # ---- split (host, per k — the multi-k reuse query) ------
            t0 = time.perf_counter()
            self._enter_phase("split")
            sp = self._phase_span("split")
            try:
                parent = elim_ops.minp_to_parent(minp_host, order, n)
                w = deg_host.astype(np.float64) \
                    if spec.weights == "degree" else None
                assigns = {}
                for k in spec.ks:
                    assigns[k] = split_ops.tree_split_host(
                        parent, pos_host, k, weights=w,
                        alpha=spec.alpha)
            finally:
                sp.end()
            t_phase["split"] = time.perf_counter() - t0
            yield "split"

            # ---- score: ONE stream pass for every k -----------------
            t0 = time.perf_counter()
            self._enter_phase("score")
            sp = self._phase_span("score")
            dev_assign = {
                k: jnp.concatenate([jnp.asarray(a, dtype=jnp.int32),
                                    jnp.zeros(1, dtype=jnp.int32)])
                for k, a in assigns.items()}
            cut = {k: 0 for k in assigns}
            cv_chunks: dict = {k: [] for k in assigns}
            total = 0
            score_start = 0
            if resume_phase == "score":
                score_start = int(state.chunk_idx)
                total = int(state.arrays["total"])
                for k in assigns:
                    cut[k] = int(state.arrays[f"cut_k{k}"])
                    if spec.comm_volume:
                        cv_chunks[k] = [state.arrays[f"cv_k{k}"]]
            elif self.ckpt is not None:
                # bank build completion at score entry: a crash before
                # the first cadence save must not re-fold the build
                # tail from an older build checkpoint
                self._save_score(0, minp_host, deg_host, cut, total,
                                 cv_chunks, total_rounds, meta)
            idx = score_start
            chunks = _device_chunks(es, cs, n, self.cache, score_start,
                                    self.ring, stats)
            try:
                for padded in chunks:
                    first = True
                    for k, a_dev in dev_assign.items():
                        c, tt = score_ops.score_chunk(padded, a_dev, n)
                        # designed per-chunk score pull (two scalars)
                        cut[k] += int(c)  # sheeplint: sync-ok
                        if first:
                            total += int(tt)  # sheeplint: sync-ok
                            first = False
                        if spec.comm_volume:
                            score_ops.accumulate_cv_keys(
                                cv_chunks[k],
                                score_ops.cut_pair_keys_host(
                                    padded, a_dev, n, k))
                    idx += 1
                    if self.ckpt is not None and (
                            self.ckpt.due(idx - score_start)
                            or self._ckpt_request):
                        self._save_score(idx, minp_host, deg_host, cut,
                                         total, cv_chunks,
                                         total_rounds, meta)
                    stats_acc.absorb(stats)
                    yield "score"
            finally:
                chunks.close()
                sp.end()
            t_phase["score"] = time.perf_counter() - t0

            if spec.resident:
                # resident partition (ISSUE 15): wrap the finished
                # build's artifacts into an incremental PartitionState
                # — the converged carried table the tenant will stream
                # delta epochs at. A delta: input seeds the state at
                # the log's epoch (state_from_build handles both).
                from sheep_tpu import incremental as inc_mod

                job.incremental_state = inc_mod.state_from_build(
                    es, spec.ks, spec.weights, spec.alpha, cs,
                    "sheepd", pos_host, deg_host, minp_host, total,
                    base_spec=spec.input)
                # seed the incremental score cache from the build's
                # own full scoring pass (ISSUE 17): the tenant's
                # FIRST scored epoch is then O(delta) too, instead of
                # paying a seeding O(E) pass on the update path. Best
                # effort — a failed seed just means refresh() stays
                # on full passes until one seeds it.
                inc_mod._seed_score_cache(
                    job.incremental_state, assigns,
                    {k: (cut[k], total) for k in spec.ks})

        from sheep_tpu.core import pure

        results = []
        for k in spec.ks:
            cv = int(len(ckpt_mod.compact_cv_keys(cv_chunks[k]))) \
                if spec.comm_volume else None
            bal = pure.part_balance(
                assigns[k], k,
                deg_host if spec.weights == "degree" else None)
            results.append(PartitionResult(
                assignment=assigns[k], k=k, edge_cut=cut[k],
                total_edges=total,
                cut_ratio=cut[k] / max(total, 1), balance=bal,
                comm_volume=cv, phase_times=dict(t_phase),
                backend="sheepd",
                diagnostics={kk: (round(float(v), 3)
                                  if str(kk).startswith("t_")
                                  or str(kk).endswith("_ms")
                                  else float(v))
                             for kk, v in stats.items()
                             if isinstance(v, (int, float))}))
        for r in results:
            # the quality plane (ISSUE 13): the served job's final
            # scores land in the trace + the job's flight ring the
            # moment they exist; the scheduler turns them into the
            # sheep_quality_* series at finalize
            obs.event("job_quality", job=job.id, k=int(r.k),
                      cut_ratio=round(float(r.cut_ratio), 6),
                      balance=round(float(r.balance), 4),
                      edge_cut=int(r.edge_cut))
        job.results = results
