"""Content-addressed result store for sheepd (ISSUE 16 tentpole a).

Persists each DONE job's final assignment + score rows keyed by the
journal's deterministic spec+input digest (:func:`journal.job_digest`),
so a repeat ``submit`` for the same digest answers from the store with
zero dispatch steps and zero recompiles, bit-identical to the original
build.

Layout: one JSON file per digest under ``<state_dir>/results/``::

    {"v": 1, "digest": ..., "t": ..., "tenant": ..., "n_vertices": ...,
     "results": [{...summary fields..., "assignment": {b64,n,dtype}}],
     "sha": sha256-over-the-canonical-body-without-"sha"}

Durability contract (mirrors the journal's):

* **Atomic publish** — entries land via tmp-write + fsync +
  ``os.replace``; a kill -9 mid-write leaves only a ``.tmp`` orphan
  (swept on open), never a half-visible entry.
* **Self-verifying reads** — every load recomputes the embedded body
  checksum. Damage (torn tail, partial write, bit rot) follows
  ``SHEEP_IO_POLICY``: strict raises :class:`ResultStoreError`,
  quarantine warns, removes the entry and reports a miss — the same
  quarantine-or-raise contract as journal replay. A damaged cache
  entry can only ever cost a rebuild, never serve a wrong answer.
* **Journal-linked ordering** — the scheduler publishes an entry only
  AFTER the job's fsync'd journal terminal, so a crash between the two
  resolves to a rebuild on the next identical submit (the journal's
  DONE carries summaries but no assignment payload), never a torn or
  unjournaled answer.

Capacity: ``max_bytes`` bounds the directory; ``put`` evicts
oldest-first (entry mtime — publish order) until the new entry fits.
``max_bytes=0`` disables the store (every get misses, puts no-op).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

STORE_VERSION = 1
_SUFFIX = ".json"
_TMP_SUFFIX = ".tmp"


class ResultStoreError(ValueError):
    """Store entry damage under SHEEP_IO_POLICY=strict."""


def _warn(msg: str) -> None:
    """Degradation warning: stderr + trace event (no-op untraced),
    mirroring journal._warn."""
    import sys

    print(f"resultstore warning: {msg}", file=sys.stderr)
    from sheep_tpu import obs

    obs.event("resultstore_degraded", message=msg)


def _body_sha(body: Dict) -> str:
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class ResultStore:
    """Digest-keyed entry directory with bounded bytes and
    oldest-first eviction. All methods are safe to call from the
    dispatch thread and handler threads under the scheduler lock; the
    store itself does no locking (one writer by construction — entries
    are immutable once published)."""

    def __init__(self, root: str, max_bytes: int = 256 << 20):
        self.root = root
        self.max_bytes = int(max_bytes)
        self.evictions = 0
        if self.max_bytes > 0:
            os.makedirs(root, exist_ok=True)
            self._sweep_tmp()

    # -- internals -----------------------------------------------------
    def _path(self, digest: str) -> str:
        # digests are hex sha1 from journal.job_digest; refuse anything
        # that could traverse out of the store directory
        if not digest or not all(c in "0123456789abcdef" for c in digest):
            raise ValueError(f"bad digest {digest!r}")
        return os.path.join(self.root, digest + _SUFFIX)

    def _sweep_tmp(self) -> None:
        """Drop publish orphans from a crash mid-write; they were never
        visible and carry no promise."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if name.endswith(_TMP_SUFFIX):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass

    def _entries(self):
        """[(mtime, size, path)] oldest first; best-effort (a racing
        eviction simply shortens the list)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            p = os.path.join(self.root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime_ns, int(st.st_size), p))
        out.sort()
        return out

    def _damaged(self, path: str, why: str) -> None:
        from sheep_tpu.io.edgestream import _io_policy

        if _io_policy() == "strict":
            raise ResultStoreError(
                f"{path}: damaged result-store entry ({why}) (set "
                f"SHEEP_IO_POLICY=quarantine to drop it and rebuild)")
        _warn(f"{path}: damaged entry dropped ({why}); the job rebuilds")
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- public API ----------------------------------------------------
    @property
    def bytes_used(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def get(self, digest: str) -> Optional[Dict]:
        """The stored entry body for ``digest``, or None (miss). A
        checksum-damaged entry is a miss under quarantine policy and a
        :class:`ResultStoreError` under strict."""
        if self.max_bytes <= 0:
            return None
        path = self._path(digest)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (ValueError, UnicodeDecodeError, OSError) as e:
            self._damaged(path, f"unparseable: {e}")
            return None
        if not isinstance(doc, dict) or not isinstance(doc.get("sha"), str):
            self._damaged(path, "missing checksum")
            return None
        v = doc.get("v")
        if not isinstance(v, int) or v > STORE_VERSION:
            _warn(f"{path}: entry v{v!r} from a newer sheep_tpu "
                  f"skipped (this daemon speaks v{STORE_VERSION})")
            return None
        body = {k: doc[k] for k in doc if k != "sha"}
        if _body_sha(body) != doc["sha"]:
            self._damaged(path, "checksum mismatch")
            return None
        if doc.get("digest") != digest:
            self._damaged(path, f"digest mismatch ({doc.get('digest')!r})")
            return None
        return doc

    def put(self, digest: str, entry: Dict) -> bool:
        """Publish ``entry`` (checksummed, atomic). Evicts oldest
        entries until the new one fits; an entry larger than the whole
        cap is refused (False) rather than flushing the store for a
        single tenant's giant assignment."""
        if self.max_bytes <= 0:
            return False
        path = self._path(digest)
        body = dict(entry)
        body["v"] = STORE_VERSION
        body["digest"] = digest
        body.pop("sha", None)
        body["sha"] = _body_sha({k: body[k] for k in body if k != "sha"})
        blob = (json.dumps(body, sort_keys=True, separators=(",", ":"))
                + "\n").encode("utf-8")
        if len(blob) > self.max_bytes:
            _warn(f"{digest}: entry of {len(blob)} bytes exceeds the "
                  f"{self.max_bytes}-byte store cap; not cached")
            return False
        # oldest-first eviction until the new entry fits the cap
        entries = self._entries()
        used = sum(size for _, size, _ in entries)
        for _, size, old in entries:
            if used + len(blob) <= self.max_bytes:
                break
            if old == path:
                used -= size  # replacing ourselves frees our old bytes
                continue
            try:
                os.unlink(old)
            except OSError:
                continue
            used -= size
            self.evictions += 1
        tmp = path + _TMP_SUFFIX
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(blob.decode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            _warn(f"{digest}: publish failed ({e}); the entry is skipped")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        return True
