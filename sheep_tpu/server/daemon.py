"""sheepd — the resident partition daemon (ISSUE 10 tentpole).

    sheepd --socket /run/sheepd.sock [--trace t.jsonl] [...]
    sheepd --port 7433 [--host 127.0.0.1]
    sheepd ... --metrics-port 9090     # + HTTP GET /metrics scraping
    sheep serve ...            # same thing, via the main CLI

One process holds the warm jit caches, the device chunk cache and the
admission scheduler (:mod:`sheep_tpu.server.scheduler`); connections
speak the newline-JSON protocol (:mod:`sheep_tpu.server.protocol`).
Thread model: one accept loop, one handler thread per connection
(handlers only touch the scheduler's locked API — a slow client can
never stall the dispatch chain), one dispatch thread stepping the
admitted jobs.

Faults in a served job degrade THAT job (the per-job retry/degrade
layer in the engine, ISSUE 9 reused); a handler or protocol error is
answered on the wire; only a failure of the daemon's own bring-up
(socket bind, trace sink) is fatal. ``shutdown`` (or SIGINT) runs
the clean path: cancel-or-drain the jobs, end every span, stop the
heartbeat, close the tracer — a clean shutdown leaves a trace with
ZERO unclosed spans (tools/obs_smoke.sh leg 6 gates this).

Durability (ISSUE 14): ``--state-dir`` arms the crash-safe job
journal + per-job checkpoints, making sheepd restart-survivable —
kill -9 the daemon mid-build, restart it on the same socket and
state dir, and the admitted jobs come back: queued ones re-queue,
running ones RESUME from their last checkpoint, bit-identical to an
uninterrupted served build. SIGTERM on a durable daemon is a
graceful drain (``--drain-grace-s``): stop admitting, checkpoint
running jobs at their next flush barrier, journal the handoff, exit
0. An exclusive flock'd pidfile under the state dir (or next to the
unix socket) keeps two sheepds from ever sharing one socket/journal
— the stale-socket probe alone races a concurrent starter.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading
from typing import Optional

from sheep_tpu.server import protocol


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sheepd",
        description="resident partition server: warm compiled programs, "
                    "device chunk cache, membudget-aware multi-tenant "
                    "job queue")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="unix socket path to listen on")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port to listen on (local use; no auth)")
    p.add_argument("--host", default="127.0.0.1",
                   help="TCP bind address (default 127.0.0.1)")
    p.add_argument("--budget-bytes", type=int, default=None,
                   help="admission budget in device bytes (default: "
                        "SHEEP_CACHE_BYTES, else 90%% of reported HBM, "
                        "else unlimited on cpu-jax)")
    p.add_argument("--trace", default=None, metavar="FILE",
                   help="append the obs trace (manifest, per-job span "
                        "trees, heartbeats) to FILE")
    p.add_argument("--heartbeat-secs", type=float, default=None,
                   metavar="S",
                   help="with --trace: periodic progress heartbeats "
                        "(inside sheepd they carry queue depth + "
                        "active-job service pressure)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="N",
                   help="serve Prometheus text on HTTP GET /metrics "
                        "at this port (0 = pick a free one; the bound "
                        "port is printed on stderr)")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   help="metrics HTTP bind address (default "
                        "127.0.0.1)")
    p.add_argument("--state-dir", default=None, metavar="DIR",
                   help="durability root (ISSUE 14): arms the crash-"
                        "safe job journal (DIR/journal.jsonl), the "
                        "exclusive daemon lockfile, and per-job "
                        "checkpoints (DIR/ckpt unless "
                        "--checkpoint-dir); on startup the journal "
                        "replays, queued jobs re-admit and running "
                        "jobs RESUME from their checkpoints")
    p.add_argument("--result-cache-bytes", type=int,
                   default=256 << 20, metavar="N",
                   help="with --state-dir: byte cap of the content-"
                        "addressed result store (STATE_DIR/results) — "
                        "repeat submits for an identical spec+input "
                        "digest answer from it with zero build steps "
                        "and zero recompiles; entries evict oldest-"
                        "first (default 256 MiB; 0 disables)")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="with --state-dir: per-job checkpoint root "
                        "(default STATE_DIR/ckpt)")
    p.add_argument("--checkpoint-every", type=int, default=16,
                   metavar="N",
                   help="with --state-dir: served checkpoint cadence "
                        "in chunks/groups (default 16)")
    p.add_argument("--drain-grace-s", type=float, default=10.0,
                   metavar="S",
                   help="SIGTERM grace (durable daemons): stop "
                        "admitting, checkpoint running jobs at their "
                        "next flush barrier, journal the handoff, "
                        "exit 0 (default 10s); without --state-dir "
                        "SIGTERM cancels jobs as before")
    return p


class Daemon:
    def __init__(self, args):
        self.args = args
        self._sock: socket.socket = None
        self._threads: list = []
        self._shutdown_evt = threading.Event()
        self.scheduler = None
        self._root_span = None
        self._metrics_httpd = None
        self.metrics_port = None  # actual bound port, once listening
        self._lock_fd = None
        self._lock_path = None

    # -- exclusive daemon lock (ISSUE 14 satellite) --------------------
    def _acquire_lock(self) -> None:
        """Serialize daemon startup per state-dir/socket with an
        exclusive flock'd pidfile. The stale-socket probe alone RACES
        a concurrent starter (two probes can both see a dead socket,
        both unlink, both bind — and then share one journal); the
        kernel lock is race-free and self-releasing on any death,
        including SIGKILL. Held for the daemon's lifetime."""
        import fcntl

        a = self.args
        if a.state_dir is not None:
            self._lock_path = os.path.join(a.state_dir, "sheepd.lock")
        elif a.socket is not None:
            self._lock_path = a.socket + ".lock"
        else:
            return  # TCP without state: the port bind is exclusive
        fd = os.open(self._lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            try:
                held_by = os.read(fd, 64).decode("ascii",
                                                 "replace").strip()
            except OSError:
                held_by = "?"
            os.close(fd)
            raise SystemExit(
                f"sheepd: {self._lock_path} is held by a live sheepd "
                f"(pid {held_by or '?'}); two daemons must not share "
                f"one socket/journal")
        os.ftruncate(fd, 0)
        os.write(fd, f"{os.getpid()}\n".encode("ascii"))
        os.fsync(fd)
        self._lock_fd = fd

    def _release_lock(self) -> None:
        # close releases the flock; the file itself stays (unlinking
        # it would re-open the open/lock race for waiters holding the
        # old inode — a stale lockFILE is harmless, only the kernel
        # lock matters and that dies with the fd/process)
        if self._lock_fd is None:
            return
        try:
            os.close(self._lock_fd)
        except OSError:
            pass
        self._lock_fd = None

    # -- telemetry HTTP listener (ISSUE 11) ----------------------------
    def _start_metrics_http(self):
        """Minimal scrape endpoint: GET /metrics answers the same
        Prometheus text as the `metrics` protocol verb, so any scraper
        (or a future replica router) can poll a running sheepd without
        speaking the line protocol. Serves nothing else; runs on its
        own daemon threads; never touches the dispatch chain beyond
        the scheduler's locked render."""
        import http.server

        from sheep_tpu.obs import metrics as metrics_mod

        daemon = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.rstrip("/") not in ("/metrics", ""):
                    self.send_error(404, "only /metrics lives here")
                    return
                try:
                    body = daemon.scheduler.render_metrics() \
                        .encode("utf-8")
                except Exception as e:  # noqa: BLE001 — answered
                    self.send_error(
                        500, f"render failed: {type(e).__name__}")
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 metrics_mod.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not log traffic
                pass

        httpd = http.server.ThreadingHTTPServer(
            (self.args.metrics_host, self.args.metrics_port), Handler)
        httpd.daemon_threads = True
        self._metrics_httpd = httpd
        self.metrics_port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever,
                             daemon=True, name="sheepd-metrics-http")
        t.start()
        print(f"sheepd: metrics on http://{self.args.metrics_host}:"
              f"{self.metrics_port}/metrics",
              file=sys.stderr, flush=True)

    # -- wire ----------------------------------------------------------
    def _bind(self) -> socket.socket:
        a = self.args
        if (a.socket is None) == (a.port is None):
            raise SystemExit("sheepd: pass exactly one of --socket PATH "
                             "or --port N")
        if a.socket is not None:
            # a stale socket file from a dead daemon would fail the
            # bind; connect-probe it so we never steal a live one
            if os.path.exists(a.socket):
                probe = socket.socket(socket.AF_UNIX)
                try:
                    probe.settimeout(0.5)
                    probe.connect(a.socket)
                except OSError:
                    os.unlink(a.socket)
                else:
                    probe.close()
                    raise SystemExit(f"sheepd: {a.socket} already has a "
                                     f"live daemon")
                finally:
                    probe.close()
            s = socket.socket(socket.AF_UNIX)
            s.bind(a.socket)
        else:
            s = socket.socket(socket.AF_INET)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((a.host, a.port))
        s.listen(64)
        return s

    def _accept_loop(self) -> None:
        while not self._shutdown_evt.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by shutdown
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="sheepd-conn")
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            rf = conn.makefile("rb")
            # chunked-update staging (ISSUE 17): transactions live on
            # THIS connection's stack frame and nowhere else — a client
            # dying mid-stream (no commit) drops its uncommitted chunks
            # with the frame, leaving the resident at its prior epoch
            txns: dict = {}
            try:
                while True:
                    try:
                        line = protocol.read_line(rf)
                    except protocol.ProtocolError as e:
                        conn.sendall(protocol.dumps(
                            {"ok": False, "error": str(e)}))
                        return
                    if line is None:
                        return
                    if not line.strip():
                        continue
                    verb = "malformed"
                    try:
                        req = protocol.parse_request(line)
                        verb = req["op"]
                        resp = self._dispatch(req, txns=txns)
                    except protocol.ProtocolError as e:
                        resp = {"ok": False, "error": str(e)}
                    except Exception as e:  # noqa: BLE001 — answered
                        resp = {"ok": False,
                                "error": f"internal: {type(e).__name__}: "
                                         f"{str(e)[:300]}"}
                    # SLO denominators (ISSUE 18): every answered wire
                    # request lands on sheepd_requests_total{verb,
                    # outcome} — what fleet error-rate bounds divide by
                    sched = self.scheduler
                    if sched is not None:
                        sched.record_request(
                            verb, "ok" if resp.get("ok") else "error")
                    try:
                        conn.sendall(protocol.dumps(resp))
                    except OSError:
                        return  # peer went away mid-answer
            finally:
                rf.close()

    # -- ops -----------------------------------------------------------
    def _dispatch(self, req: dict,
                  txns: Optional[dict] = None) -> dict:
        op = req["op"]
        sched = self.scheduler
        # propagated trace context (ISSUE 18): validated here so a
        # malformed traceparent is answered loudly, never silently
        # mis-correlated; threaded into the job's detached span +
        # flight ring at submit
        trace = None
        if req.get("trace") is not None:
            trace = protocol.parse_traceparent(req["trace"])
        if op == "update" and req.get("stream") is not None:
            return self._update_stream(req, txns)
        if op == "ping":
            return {"ok": True, "pid": os.getpid(),
                    "uptime_s": sched.stats()["uptime_s"]}
        if op == "submit":
            spec = protocol.JobSpec.from_request(
                req.get("job"), tenant=req.get("tenant", "default"))
            if req.get("reattach"):
                # idempotent resubmission (ISSUE 14): a retried submit
                # reattaches to the journaled/live twin by spec digest
                # instead of double-building
                job, reattached = sched.reattach_or_submit(
                    spec, trace=trace)
            else:
                job, reattached = sched.submit(spec,
                                               trace=trace), False
            return {"ok": True, "job_id": job.id, "state": job.state,
                    **({"reattached": True} if reattached else {}),
                    **({"error": job.error} if job.error else {})}
        if op in ("status", "wait", "cancel"):
            job_id = req.get("job_id")
            if not job_id:
                raise protocol.ProtocolError(f"{op} needs job_id")
            if op == "cancel":
                state = sched.cancel(job_id)
                if state is None:
                    raise protocol.ProtocolError(
                        f"unknown job {job_id!r}")
                return {"ok": True, "job_id": job_id, "state": state}
            if op == "wait":
                job = sched.wait(job_id,
                                 timeout_s=req.get("timeout_s"))
            else:
                job = sched.get(job_id)
            if job is None:
                raise protocol.ProtocolError(f"unknown job {job_id!r}")
            return {"ok": True, "job": job.descriptor(with_results=True)}
        if op == "list":
            return {"ok": True,
                    "jobs": [j.descriptor() for j in sched.jobs()]}
        if op == "stats":
            return {"ok": True, "stats": sched.stats()}
        if op == "metrics":
            from sheep_tpu.obs import metrics as metrics_mod

            return {"ok": True,
                    "content_type": metrics_mod.CONTENT_TYPE,
                    "text": sched.render_metrics()}
        if op == "lookup":
            # fleet verb (ISSUE 16): does this replica's result store
            # hold the digest? A multi-endpoint client probes every
            # replica; any hit short-circuits headroom routing.
            digest = req.get("digest")
            if not digest or not isinstance(digest, str):
                raise protocol.ProtocolError(
                    "lookup needs a 'digest' string")
            return {"ok": True, "digest": digest,
                    "hit": bool(sched.lookup_digest(digest))}
        if op in ("update", "epoch", "compact"):
            # resident-partition verbs (ISSUE 15): executed on the
            # dispatch thread; this handler just parks on the answer
            job_id = req.get("job_id")
            if not job_id:
                raise protocol.ProtocolError(f"{op} needs job_id")
            if op == "epoch":
                return {"ok": True, **sched.epoch_info(job_id)}
            if op == "compact":
                return {"ok": True, **sched.compact_resident(
                    job_id, mode=req.get("mode", "auto"),
                    score=bool(req.get("score", False)))}
            adds = protocol.decode_edges(req.get("adds")) \
                if req.get("adds") is not None else None
            dels = protocol.decode_edges(req.get("dels")) \
                if req.get("dels") is not None else None
            log = req.get("log")
            if log is not None and not isinstance(log, str):
                raise protocol.ProtocolError(
                    "update.log must be a daemon-side path")
            if log is None and adds is None and dels is None:
                raise protocol.ProtocolError(
                    "update needs adds/dels payloads or a log path")
            epoch = req.get("epoch")
            if epoch is not None:
                try:
                    epoch = int(epoch)
                except (TypeError, ValueError):
                    raise protocol.ProtocolError(
                        "update.epoch must be an integer") from None
            return {"ok": True, **sched.update(
                job_id, adds=adds, dels=dels, epoch=epoch,
                score=bool(req.get("score", False)),
                compact=str(req.get("compact", "auto")), log=log)}
        if op == "profile":
            pdir = req.get("dir")
            if not pdir or not isinstance(pdir, str):
                raise protocol.ProtocolError(
                    "profile needs a daemon-side directory in 'dir'")
            info = sched.arm_profile(pdir, steps=req.get("steps", 8))
            return {"ok": True, "profile": info}
        if op == "shutdown":
            if req.get("suspend"):
                # the SIGTERM graceful drain, reachable on the wire:
                # checkpoint + journal the running jobs, then exit 0
                if sched.journal is None:
                    raise protocol.ProtocolError(
                        "shutdown suspend needs a durable daemon "
                        "(--state-dir)")
                sched.shutdown_suspend(
                    float(req.get("grace_s",
                                  self.args.drain_grace_s)))
                self._shutdown_evt.set()
                return {"ok": True, "suspending": True}
            drain = bool(req.get("drain", False))
            sched.shutdown(drain=drain)
            self._shutdown_evt.set()
            return {"ok": True, "draining": drain}
        raise protocol.ProtocolError(f"unhandled op {op!r}")

    def _update_stream(self, req: dict,
                       txns: Optional[dict]) -> dict:
        """Chunked ``update`` framing (ISSUE 17).

        Staged payloads live in ``txns`` — the calling connection's
        dict — so a torn stream (client death, no commit) is discarded
        with the connection and changes nothing server-side. Only
        ``commit`` touches the scheduler, and it does so through the
        exact same ``sched.update`` path as a single-shot update.
        """
        import numpy as np

        if txns is None:
            raise protocol.ProtocolError(
                "chunked update is connection-scoped")
        verb = req.get("stream")
        if verb not in protocol.UPDATE_STREAM_VERBS:
            raise protocol.ProtocolError(
                f"update.stream must be one of "
                f"{protocol.UPDATE_STREAM_VERBS}, got {verb!r}")
        if verb == "begin":
            job_id = req.get("job_id")
            if not job_id:
                raise protocol.ProtocolError(
                    "update stream begin needs job_id")
            txns["seq"] = txns.get("seq", 0) + 1
            txn = f"u{txns['seq']}"
            txns.setdefault("open", {})[txn] = {
                "job_id": job_id, "adds": [], "dels": [], "bytes": 0}
            return {"ok": True, "txn": txn, "job_id": job_id}
        txn = req.get("txn")
        st = txns.get("open", {}).get(txn)
        if st is None:
            raise protocol.ProtocolError(
                f"unknown update txn {txn!r} (transactions are "
                f"connection-scoped: begin/chunk/commit must share "
                f"one connection)")
        if verb == "abort":
            del txns["open"][txn]
            return {"ok": True, "txn": txn, "aborted": True}
        if verb == "chunk":
            adds = protocol.decode_edges(req.get("adds")) \
                if req.get("adds") is not None else None
            dels = protocol.decode_edges(req.get("dels")) \
                if req.get("dels") is not None else None
            if adds is None and dels is None:
                raise protocol.ProtocolError(
                    "update stream chunk needs adds and/or dels")
            nbytes = 16 * ((0 if adds is None else len(adds)) +
                           (0 if dels is None else len(dels)))
            if st["bytes"] + nbytes > protocol.MAX_UPDATE_TXN_BYTES:
                del txns["open"][txn]  # poisoned — force a fresh begin
                raise protocol.ProtocolError(
                    f"update txn {txn} exceeds "
                    f"{protocol.MAX_UPDATE_TXN_BYTES} staged bytes; "
                    f"txn aborted")
            if adds is not None and len(adds):
                st["adds"].append(adds)
            if dels is not None and len(dels):
                st["dels"].append(dels)
            st["bytes"] += nbytes
            return {"ok": True, "txn": txn,
                    "adds": int(sum(len(a) for a in st["adds"])),
                    "dels": int(sum(len(d) for d in st["dels"]))}
        # commit: fold every staged chunk as ONE epoch
        del txns["open"][txn]
        adds = np.concatenate(st["adds"]) if st["adds"] else None
        dels = np.concatenate(st["dels"]) if st["dels"] else None
        if adds is None and dels is None:
            raise protocol.ProtocolError(
                f"update txn {txn} committed with no staged edges")
        epoch = req.get("epoch")
        if epoch is not None:
            try:
                epoch = int(epoch)
            except (TypeError, ValueError):
                raise protocol.ProtocolError(
                    "update.epoch must be an integer") from None
        return {"ok": True, "txn": txn, **self.scheduler.update(
            st["job_id"], adds=adds, dels=dels, epoch=epoch,
            score=bool(req.get("score", False)),
            compact=str(req.get("compact", "auto")))}

    # -- lifecycle -----------------------------------------------------
    def serve(self) -> int:
        from sheep_tpu.utils.platform import (enable_compilation_cache,
                                              pin_platform)

        pin_platform()
        enable_compilation_cache()
        from sheep_tpu import obs
        from sheep_tpu.server.scheduler import Scheduler

        a = self.args
        journal_path = None
        ckpt_dir = a.checkpoint_dir
        result_store = None
        if a.state_dir is not None:
            os.makedirs(a.state_dir, exist_ok=True)
            journal_path = os.path.join(a.state_dir, "journal.jsonl")
            if ckpt_dir is None:
                ckpt_dir = os.path.join(a.state_dir, "ckpt")
            if getattr(a, "result_cache_bytes", 0) > 0:
                # fleet warm path (ISSUE 16): the content-addressed
                # result store shares the durability root — entries
                # publish only after the journal terminal lands
                from sheep_tpu.server.resultstore import ResultStore

                result_store = ResultStore(
                    os.path.join(a.state_dir, "results"),
                    max_bytes=a.result_cache_bytes)
        elif ckpt_dir is not None:
            raise SystemExit("sheepd: --checkpoint-dir needs "
                             "--state-dir (checkpoints cannot resume "
                             "jobs a lost journal forgot)")
        # the exclusive lock comes BEFORE the stale-socket probe: two
        # concurrent starters must serialize on the kernel lock, not
        # race the probe/unlink/bind window
        self._acquire_lock()
        tracer = None
        if a.trace:
            tracer = obs.install(obs.Tracer(a.trace))
            obs.emit_manifest(tracer, config=vars(a), backend="sheepd")
        root_span = obs.begin("serve")
        self._root_span = root_span
        try:
            self.scheduler = Scheduler(
                budget_bytes=a.budget_bytes,
                root_span_id=getattr(root_span, "id", None),
                journal=journal_path, checkpoint_dir=ckpt_dir,
                checkpoint_every=a.checkpoint_every,
                result_store=result_store)
            if tracer is not None and a.heartbeat_secs:
                # started after the scheduler exists so each beat can
                # sample its queue depth / active jobs: soak logs show
                # SERVICE pressure, not just per-run progress
                tracer.heartbeat = obs.Heartbeat(
                    tracer, a.heartbeat_secs,
                    service=self.scheduler.service_pressure).start()
            if a.metrics_port is not None:
                self._start_metrics_http()
            self._sock = self._bind()
            addr = a.socket if a.socket is not None \
                else f"{a.host}:{a.port}"
            print(f"sheepd: listening on {addr} (budget="
                  f"{self.scheduler.budget or 'unlimited'})",
                  file=sys.stderr, flush=True)

            def _sig_int(_num, _frame):
                self.scheduler.shutdown(drain=False)
                self._shutdown_evt.set()

            def _sig_term(_num, _frame):
                # SIGTERM on a durable daemon is the graceful drain
                # (ISSUE 14): checkpoint running jobs at their next
                # flush barrier, journal the handoff, exit 0 — the
                # next incarnation resumes them. Non-durable daemons
                # keep the old cancel semantics.
                if self.scheduler.journal is not None:
                    self.scheduler.shutdown_suspend(a.drain_grace_s)
                else:
                    self.scheduler.shutdown(drain=False)
                self._shutdown_evt.set()

            try:
                signal.signal(signal.SIGTERM, _sig_term)
                signal.signal(signal.SIGINT, _sig_int)
            except ValueError:
                pass  # not the main thread (embedded/test use)
            acceptor = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="sheepd-accept")
            acceptor.start()
            # the dispatch loop runs on THIS thread until shutdown
            self.scheduler.run()
            self._shutdown_evt.set()
            return 0
        finally:
            if self._metrics_httpd is not None:
                try:
                    self._metrics_httpd.shutdown()
                    self._metrics_httpd.server_close()
                except OSError:
                    pass
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            if a.socket and os.path.exists(a.socket):
                try:
                    os.unlink(a.socket)
                except OSError:
                    pass
            root_span.end()
            if tracer is not None:
                if tracer.heartbeat is not None:
                    tracer.heartbeat.stop()
                obs.uninstall()
                tracer.close()
            self._release_lock()
            print("sheepd: shut down cleanly", file=sys.stderr,
                  flush=True)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return Daemon(args).serve()


if __name__ == "__main__":
    sys.exit(main())
