"""sheeptop — a live console view over a running sheepd (ISSUE 11).

    sheeptop --server /run/sheepd.sock            # curses refresh view
    sheeptop --server 127.0.0.1:7433 --plain      # line-mode refresh
    sheeptop --server ... --once                  # one snapshot, exit 0
    sheeptop --endpoints /run/a.sock,/run/b.sock  # fleet mode

Polls the ``metrics`` + ``list`` protocol verbs (no HTTP needed — it
speaks the same line protocol as sheep-submit) and renders:

- daemon headroom: uptime, queue depth, active jobs, reserved vs
  budget bytes, device memory, flight-recorder dumps;
- per-tenant SLO lines: request count and p50/p90/p99 latency
  estimated from the ``sheepd_request_latency_seconds`` histogram
  buckets;
- per-job rows: id, tenant, state, live phase, steps, wall seconds,
  and — once a job is done — its final cut ratio and balance from the
  descriptor's result summaries (the quality plane, ISSUE 13).

Fleet mode (ISSUE 18): ``--endpoints A,B`` polls every replica and
renders one per-replica summary row each (up/DOWN, queue, active,
reserved, flight dumps) plus a fleet-aggregate latency table whose
p50/p90/p99 come from the FEDERATED histogram buckets
(:mod:`sheep_tpu.obs.federate` — counters sum, same-boundary buckets
add), i.e. quantiles over the union of every replica's observations,
not an average of per-replica quantiles. A replica that fails its
poll shows as DOWN and degrades out of the merge; the frame renders
either way.

Rendering is pure string assembly (:func:`render_lines` /
:func:`render_fleet_lines`) so tests pin it without a terminal;
curses is a presentation detail that degrades to plain line mode on
dumb terminals, ``--plain``, or ``--once``. The client reconnects per
poll — a daemon restart mid-watch shows as one unreachable frame, not
a dead tool.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from sheep_tpu.obs import metrics as metrics_mod
from sheep_tpu.server.client import ServerError, SheepClient


def fetch(server: str, timeout_s: float = 10.0) -> dict:
    """One poll: parsed metrics + job list from a fresh connection."""
    with SheepClient(server, timeout_s=timeout_s) as c:
        text = c.metrics()
        jobs = c.list()
    return {"metrics": metrics_mod.parse_prometheus(text),
            "jobs": jobs, "t": time.time()}


def fetch_fleet(endpoints: List[str], timeout_s: float = 10.0) -> dict:
    """One fleet poll: every replica's metrics + jobs (per-replica
    failures degrade to an up=False row), plus the federated merge of
    the scrapes that answered."""
    from sheep_tpu.obs import federate as federate_mod

    replicas = []
    scrapes = []
    for ep in endpoints:
        try:
            with SheepClient(ep, timeout_s=timeout_s) as c:
                text = c.metrics()
                jobs = c.list()
            replicas.append(
                {"endpoint": ep, "up": True, "jobs": jobs,
                 "metrics": metrics_mod.parse_prometheus(text)})
            scrapes.append((ep, text))
        except (ServerError, OSError) as e:
            replicas.append({"endpoint": ep, "up": False,
                             "error": str(e), "metrics": {},
                             "jobs": []})
            scrapes.append((ep, None))
    return {"replicas": replicas,
            "fed": federate_mod.federate(scrapes), "t": time.time()}


def _g(parsed: dict, name: str, default=None):
    rows = parsed.get(name)
    if not rows:
        return default
    return rows[0][1]


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}TiB"


def _fmt_s(v) -> str:
    return "-" if v is None else f"{float(v):.2f}s"


def tenant_slo_rows(parsed: dict) -> List[dict]:
    """Per-tenant request-latency percentiles from the scraped
    histogram buckets."""
    buckets = parsed.get("sheepd_request_latency_seconds_bucket", [])
    counts = parsed.get("sheepd_request_latency_seconds_count", [])
    tenants = sorted({lb.get("tenant") for lb, _ in counts
                      if lb.get("tenant") is not None})
    rows = []
    for tenant in tenants:
        match = {"tenant": tenant}
        n = next((v for lb, v in counts
                  if lb.get("tenant") == tenant), 0)
        rows.append({
            "tenant": tenant, "requests": int(n),
            "p50": metrics_mod.histogram_series_quantile(
                buckets, 0.5, match),
            "p90": metrics_mod.histogram_series_quantile(
                buckets, 0.9, match),
            "p99": metrics_mod.histogram_series_quantile(
                buckets, 0.99, match),
        })
    return rows


def render_lines(model: dict, width: int = 100) -> List[str]:
    """The whole screen as plain strings (shared by curses and plain
    modes, pinned by tests)."""
    parsed = model["metrics"]
    jobs = model["jobs"]
    lines = []
    up = _g(parsed, "sheepd_uptime_seconds")
    reserved = _g(parsed, "sheepd_reserved_bytes")
    budget = _g(parsed, "sheepd_budget_bytes")
    lines.append(
        f"sheepd up {up if up is None else int(up)}s  "
        f"queue={int(_g(parsed, 'sheepd_queue_depth', 0))}  "
        f"active={int(_g(parsed, 'sheepd_active_jobs', 0))}  "
        f"reserved={_fmt_bytes(reserved)}"
        + (f"/{_fmt_bytes(budget)}" if budget is not None else "")
        + f"  flight_dumps="
          f"{int(_g(parsed, 'sheepd_flight_dumps', 0))}")
    mem = _g(parsed, "sheepd_device_bytes_in_use")
    peak = _g(parsed, "sheepd_device_peak_bytes_in_use")
    if mem is not None or peak is not None:
        lines.append(f"device mem: in_use={_fmt_bytes(mem)} "
                     f"peak={_fmt_bytes(peak)}")
    slo = tenant_slo_rows(parsed)
    if slo:
        lines.append("")
        lines.append(f"{'tenant':<16}{'requests':>9}{'p50':>10}"
                     f"{'p90':>10}{'p99':>10}")
        for row in slo:
            lines.append(
                f"{row['tenant'][:15]:<16}{row['requests']:>9}"
                f"{_fmt_s(row['p50']):>10}{_fmt_s(row['p90']):>10}"
                f"{_fmt_s(row['p99']):>10}")
    lines.append("")
    lines.append(f"{'job':<8}{'tenant':<16}{'state':<19}{'phase':<9}"
                 f"{'steps':>7}  {'wall':>8}{'cut':>8}{'bal':>7}")
    now = model.get("t", time.time())
    for j in jobs:
        start = j.get("start_t")
        end = j.get("end_t")
        wall = j.get("wall_s")
        if wall is None and start is not None:
            wall = max(0.0, (end or now) - start)
        # quality columns (ISSUE 13): a done job's final score, read
        # from the descriptor's result summaries (first k of a multi-k
        # job — the full list is one `status` call away)
        cut = bal = None
        results = j.get("results") or []
        if results:
            cut = results[0].get("cut_ratio")
            bal = results[0].get("balance")
        lines.append(
            f"{str(j.get('job_id', '?'))[:7]:<8}"
            f"{str(j.get('tenant', '?'))[:15]:<16}"
            f"{str(j.get('state', '?')):<19}"
            f"{str(j.get('phase', '-')):<9}"
            f"{int(j.get('steps', 0)):>7}  "
            f"{'-' if wall is None else f'{wall:8.1f}s'}"
            f"{'-' if cut is None else f'{100 * float(cut):.2f}%':>8}"
            f"{'-' if bal is None else f'{float(bal):.3f}':>7}")
    if not jobs:
        lines.append("  (no jobs)")
    return [ln[:width] for ln in lines]


def render_fleet_lines(model: dict, width: int = 100) -> List[str]:
    """The fleet screen: one summary row per replica, then the
    fleet-aggregate latency table over MERGED histogram buckets (the
    federate record keeps the parse_prometheus shape, so
    :func:`tenant_slo_rows` reads it unchanged)."""
    reps = model["replicas"]
    fed = model["fed"]
    lines = []
    n_up = sum(1 for r in reps if r["up"])
    lines.append(f"sheep fleet: {n_up}/{len(reps)} replicas up  "
                 f"jobs={sum(len(r['jobs']) for r in reps)}")
    lines.append("")
    lines.append(f"{'replica':<40}{'up':>5}{'queue':>7}{'active':>8}"
                 f"{'reserved':>12}{'dumps':>7}")
    for r in reps:
        p = r["metrics"]
        lines.append(
            f"{r['endpoint'][-39:]:<40}"
            f"{'ok' if r['up'] else 'DOWN':>5}"
            f"{int(_g(p, 'sheepd_queue_depth', 0)):>7}"
            f"{int(_g(p, 'sheepd_active_jobs', 0)):>8}"
            f"{_fmt_bytes(_g(p, 'sheepd_reserved_bytes')):>12}"
            f"{int(_g(p, 'sheepd_flight_dumps', 0)):>7}")
    slo = tenant_slo_rows(fed["samples"])
    if slo:
        lines.append("")
        lines.append("fleet latency (federated buckets, all replicas):")
        lines.append(f"{'tenant':<16}{'requests':>9}{'p50':>10}"
                     f"{'p90':>10}{'p99':>10}")
        for row in slo:
            lines.append(
                f"{row['tenant'][:15]:<16}{row['requests']:>9}"
                f"{_fmt_s(row['p50']):>10}{_fmt_s(row['p90']):>10}"
                f"{_fmt_s(row['p99']):>10}")
    for w in fed["warnings"]:
        lines.append(f"warning: {w}")
    return [ln[:width] for ln in lines]


def _poll_lines(args, width: int = 100) -> List[str]:
    if args.endpoint_list:
        return render_fleet_lines(fetch_fleet(args.endpoint_list),
                                  width=width)
    return render_lines(fetch(args.server), width=width)


def _loop_plain(args) -> int:
    while True:
        try:
            out = "\n".join(_poll_lines(args))
        except (ServerError, OSError) as e:
            out = f"sheeptop: daemon unreachable: {e}"
        print(out, flush=True)
        if args.once:
            return 0
        print("-" * 60, flush=True)
        time.sleep(max(0.2, args.interval))


def _loop_curses(args) -> int:
    import curses

    def run(scr):
        curses.curs_set(0)
        scr.timeout(int(max(0.2, args.interval) * 1000))
        while True:
            try:
                lines = _poll_lines(
                    args, width=max(20, scr.getmaxyx()[1] - 1))
            except (ServerError, OSError) as e:
                lines = [f"sheeptop: daemon unreachable: {e}"]
            scr.erase()
            maxy = scr.getmaxyx()[0]
            for i, ln in enumerate(lines[:maxy - 1]):
                try:
                    scr.addstr(i, 0, ln)
                except curses.error:
                    break  # terminal shrank mid-draw
            try:
                scr.addstr(maxy - 1, 0, "q to quit")
            except curses.error:
                pass
            scr.refresh()
            ch = scr.getch()
            if ch in (ord("q"), ord("Q")):
                return 0

    return curses.wrapper(run)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sheeptop",
        description="live console view over a running sheepd "
                    "(metrics + list verbs)")
    p.add_argument("--server", default=None,
                   help="daemon address: unix socket path or host:port")
    p.add_argument("--endpoints", default=None, metavar="A,B",
                   help="fleet mode: comma-separated replica "
                        "addresses — per-replica rows + latency "
                        "percentiles over federated buckets")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="refresh interval (default 2s)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--plain", action="store_true",
                   help="line mode (no curses) even on a tty")
    return p


def main(argv: Optional[list] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    args.endpoint_list = [e.strip() for e in
                          (args.endpoints or "").split(",")
                          if e.strip()]
    if bool(args.server) == bool(args.endpoint_list):
        parser.error("exactly one of --server or --endpoints")
    try:
        if args.once or args.plain or not sys.stdout.isatty():
            return _loop_plain(args)
        return _loop_curses(args)
    except KeyboardInterrupt:
        return 0
    except (ServerError, OSError) as e:
        print(f"sheeptop: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
