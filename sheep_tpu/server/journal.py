"""Crash-safe job journal for sheepd (ISSUE 14 tentpole).

An append-only, newline-JSON write-ahead log of every job's lifecycle,
so a daemon crash or redeploy loses NOTHING that was admitted: on
startup the scheduler replays the journal, re-admits journaled queued
jobs, and re-admits journaled RUNNING jobs whose engines then resume
from their per-job checkpoints (``utils/checkpoint.Checkpointer``
child domains under the daemon's checkpoint dir).

Record grammar (one JSON object per line, ``rec`` selects)::

    {"v": 1, "rec": "daemon_start", "t": ..., "pid": ...}
    {"v": 1, "rec": "submit", "job_id": "j3", "t": ..., "tenant": ...,
     "digest": ..., "n_vertices": ..., "modeled_bytes": ...,
     "state": "queued"|"rejected", "error": ..., "spec": {...}}
    {"v": 1, "rec": "state", "job_id": "j3", "state": "running",
     "t": ...}
    {"v": 1, "rec": "terminal", "job_id": "j3", "state": "done",
     "t": ..., "error": ..., "results": [summaries]}
    {"v": 1, "rec": "drain", "t": ..., "suspended": [...],
     "queued": [...]}

Durability contract: ``submit`` and ``terminal`` records are fsync'd
(admission and terminal are the promises a client acts on); ``state``
records are buffered-flushed only — losing one merely replays the job
as queued, which the resume path treats as a clean start.

Replay is torn-tail tolerant like the edgestream's
``SHEEP_IO_POLICY=quarantine`` contract: a crash mid-append leaves at
most one torn trailing line, which replay drops with a warning. Damage
*before* the tail follows the IO policy proper (strict = raise,
quarantine = warn + skip). Records from a NEWER journal version, or of
an unknown ``rec`` kind, are skipped with a warning — never a crash —
so an old daemon can land on a newer journal without eating it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional

JOURNAL_VERSION = 1

# record kinds this version understands; anything else is skipped
# with a warning on replay (forward compatibility, never a crash).
# delta_epoch / resident_release (ISSUE 15) journal a RESIDENT
# partition's lifecycle: each applied delta epoch is fsync'd AFTER
# its state checkpoint lands, so a killed daemon resumes the resident
# partition at its last applied epoch; release frees the reservation.
# Both arrive after the job's DONE terminal — replay applies them
# post-terminal, unlike state records.
REC_KINDS = ("daemon_start", "submit", "state", "terminal", "drain",
             "delta_epoch", "resident_release")

_TERMINAL = ("done", "failed", "cancelled", "deadline_exceeded",
             "rejected")


class JournalError(ValueError):
    """Journal damage before the tail under SHEEP_IO_POLICY=strict."""


def _warn(msg: str) -> None:
    """Replay degradation warning: stderr + a trace event (no-op
    untraced), mirroring checkpoint.py's degradation trail."""
    import sys

    print(f"journal warning: {msg}", file=sys.stderr)
    from sheep_tpu import obs

    obs.event("journal_degraded", message=msg)


def job_digest(spec) -> str:
    """Deterministic identity of one submit: the full JobSpec plus the
    input file's content identity (size + mtime when it is a path —
    synthetic ``rmat-hash:``-style specs are self-identifying). A
    client that retries a submit against a restarted daemon sends
    ``reattach`` and this digest matches it to the journaled job
    instead of double-building."""
    body: Dict = dataclasses.asdict(spec)
    body.pop("extra", None)
    try:
        st = os.stat(spec.input)
        body["_file_size"] = int(st.st_size)
        body["_file_mtime_ns"] = int(st.st_mtime_ns)
    except OSError:
        pass
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()


@dataclasses.dataclass
class ReplayedJob:
    """One job's latest journaled state after replay."""

    job_id: str
    tenant: str
    spec: Dict                      # JobSpec fields, as journaled
    digest: Optional[str]
    submit_t: float
    n_vertices: int
    modeled_bytes: Optional[int]
    state: str                      # queued/running or a terminal state
    error: Optional[str] = None
    end_t: Optional[float] = None
    results: Optional[List[Dict]] = None   # summaries (terminal done)
    # resident-partition lineage (ISSUE 15): the last journaled
    # applied delta epoch, and whether the residency was released
    delta_epoch: int = 0
    resident_released: bool = False

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL


@dataclasses.dataclass
class Replay:
    """What a journal replays to: jobs in submit order, the id counter
    floor, and how many daemon incarnations came before this one."""

    jobs: List[ReplayedJob]
    next_id: int
    daemon_starts: int
    warnings: List[str]


class JobJournal:
    """Appender + replayer for one journal file. Appends are whole
    lines through one handle (O_APPEND semantics), so concurrent
    handler threads under the scheduler lock can never interleave
    partial records; fsync policy is per-record (see module doc)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._repair_tail()
        self._f = open(path, "a", encoding="utf-8")

    def _repair_tail(self) -> None:
        """Heal a torn tail BEFORE appending: a crash mid-append leaves
        a final line with no newline, and appending after it would glue
        the next record onto the fragment — turning a tolerated
        torn-tail into permanent mid-file damage that a strict-policy
        replay would refuse forever. A parseable unterminated record
        just gets its newline (the data is intact); garbage is
        truncated away, exactly what replay would have dropped."""
        try:
            f = open(self.path, "rb")
        except FileNotFoundError:
            return
        with f:
            data = f.read()
        if not data or data.endswith(b"\n"):
            return
        cut = data.rfind(b"\n") + 1
        tail = data[cut:]
        try:
            json.loads(tail.decode("utf-8"))
            with open(self.path, "ab") as f:
                f.write(b"\n")
                f.flush()
                os.fsync(f.fileno())
            return
        except (ValueError, UnicodeDecodeError):
            pass
        _warn(f"{self.path}: truncating torn trailing record "
              f"({len(tail)} bytes) before appending")
        with open(self.path, "r+b") as f:
            f.truncate(cut)
            f.flush()
            os.fsync(f.fileno())

    def append(self, rec: Dict, fsync: bool = False) -> None:
        rec = {"v": JOURNAL_VERSION, **rec}
        self._f.write(json.dumps(rec, separators=(",", ":"),
                                 sort_keys=True) + "\n")
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    # -- replay --------------------------------------------------------
    def replay(self) -> Replay:
        return replay(self.path)


def replay(path: str) -> Replay:
    """Replay a journal into per-job latest state (see module doc for
    the tolerance contract). Missing or empty journal = clean start."""
    from sheep_tpu.io.edgestream import _io_policy

    warnings: List[str] = []

    def warn(msg: str) -> None:
        warnings.append(msg)
        _warn(msg)

    jobs: "Dict[str, ReplayedJob]" = {}
    order: List[str] = []
    daemon_starts = 0
    max_id = 0
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except FileNotFoundError:
        return Replay(jobs=[], next_id=1, daemon_starts=0,
                      warnings=warnings)
    for i, line in enumerate(lines):
        at_tail = i == len(lines) - 1
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except ValueError as e:
            # a torn TAIL is the expected crash artifact (the append
            # died mid-line) — always dropped with a warning; damage
            # before the tail follows the IO policy proper
            if at_tail or not line.endswith("\n"):
                warn(f"{path}: torn trailing record dropped ({e})")
                continue
            if _io_policy() == "strict":
                raise JournalError(
                    f"{path}: damaged journal record at line {i + 1} "
                    f"({e}) (set SHEEP_IO_POLICY=quarantine to skip "
                    f"it and continue)") from None
            warn(f"{path}: damaged record at line {i + 1} skipped "
                 f"({e})")
            continue
        v = rec.get("v")
        if not isinstance(v, int) or v > JOURNAL_VERSION:
            warn(f"{path}: record v{v!r} from a newer sheep_tpu "
                 f"skipped (this daemon speaks v{JOURNAL_VERSION})")
            continue
        kind = rec.get("rec")
        if kind not in REC_KINDS:
            warn(f"{path}: unknown record kind {kind!r} skipped")
            continue
        if kind == "daemon_start":
            daemon_starts += 1
            continue
        if kind == "drain":
            continue  # informational: the handoff itself changes no job
        job_id = rec.get("job_id")
        if not isinstance(job_id, str) or not job_id:
            warn(f"{path}: {kind} record without job_id skipped")
            continue
        if kind == "submit":
            if job_id in jobs:
                warn(f"{path}: duplicate submit for {job_id} skipped")
                continue
            spec = rec.get("spec")
            if not isinstance(spec, dict) or not spec.get("input"):
                warn(f"{path}: submit for {job_id} carries no usable "
                     f"spec; skipped")
                continue
            jobs[job_id] = ReplayedJob(
                job_id=job_id,
                tenant=str(rec.get("tenant", "default")),
                spec=spec,
                digest=rec.get("digest"),
                submit_t=float(rec.get("t", 0.0)),
                n_vertices=int(rec.get("n_vertices", 0)),
                modeled_bytes=rec.get("modeled_bytes"),
                state=str(rec.get("state", "queued")),
                error=rec.get("error"),
            )
            order.append(job_id)
            if job_id.startswith("j"):
                try:
                    max_id = max(max_id, int(job_id[1:]))
                except ValueError:
                    pass
            continue
        job = jobs.get(job_id)
        if job is None:
            warn(f"{path}: {kind} record for unjournaled job "
                 f"{job_id} skipped")
            continue
        if kind == "delta_epoch":
            # arrives AFTER the job's DONE terminal by design (a
            # resident partition only exists once built); the newest
            # epoch wins (epochs never rewind at the appender)
            job.delta_epoch = max(job.delta_epoch,
                                  int(rec.get("epoch", 0)))
            continue
        if kind == "resident_release":
            job.resident_released = True
            continue
        if job.terminal:
            # first terminal wins: a duplicate terminal (crash between
            # the journal write and the ack) must not flip the state
            warn(f"{path}: {kind} record for already-terminal "
                 f"{job_id} skipped")
            continue
        if kind == "state":
            job.state = str(rec.get("state", job.state))
        else:  # terminal
            job.state = str(rec.get("state", "failed"))
            job.error = rec.get("error")
            job.end_t = float(rec.get("t", 0.0)) or None
            res = rec.get("results")
            job.results = res if isinstance(res, list) else None
    return Replay(jobs=[jobs[j] for j in order], next_id=max_id + 1,
                  daemon_starts=daemon_starts, warnings=warnings)
