"""sheeplint orchestration: collect files, build the cross-file index,
run every rule, apply the baseline."""

from __future__ import annotations

import ast
import os

from sheep_tpu.analysis.core import Finding
from sheep_tpu.analysis.index import build_index
from sheep_tpu.analysis.rules import check_file

SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def collect_files(paths) -> list:
    """Expand files/directories into a sorted list of .py paths."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_paths(paths, baseline: set = frozenset()):
    """Lint every .py file under ``paths``.

    Returns ``(findings, baselined_count, parse_errors)``; findings
    whose (rule, path, line) key is in ``baseline`` are filtered out
    and counted separately. Paths in findings are kept as given (the
    baseline is stable only when the tool runs from the repo root with
    relative paths — which is how the gate invokes it)."""
    files = collect_files(paths)
    sources, trees, parse_errors = {}, {}, []
    for path in files:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            trees[path] = ast.parse(src, filename=path)
            sources[path] = src
        except SyntaxError as e:
            parse_errors.append(Finding(
                rule="parse", severity="error", path=path,
                line=e.lineno or 0, message=f"syntax error: {e.msg}"))
    index = build_index(trees.values())
    findings, baselined = [], 0
    for path in files:
        if path not in trees:
            continue
        for f in check_file(path, sources[path], trees[path], index):
            if f.baseline_key() in baseline:
                baselined += 1
            else:
                findings.append(f)
    return findings + parse_errors, baselined, parse_errors


def lint_source(source: str, path: str = "<memory>",
                extra_sources=()) -> list:
    """Lint one in-memory module (the test-fixture entry point).
    ``extra_sources`` are additional modules whose jit/donate
    definitions should be visible to the index (cross-file flows)."""
    tree = ast.parse(source, filename=path)
    trees = [tree] + [ast.parse(s) for s in extra_sources]
    index = build_index(trees)
    return check_file(path, source, tree, index)
