"""sheeplint CLI (console script ``sheeplint``; also exposed as
``tools/sheeplint.py``).

Exit codes: 0 = no non-baselined findings, 1 = errors present,
2 = warnings only, 3 = usage/internal error. ``--check`` is the gate
spelling used by tier-1 (identical behavior, explicit intent)."""

from __future__ import annotations

import argparse
import json
import os
import sys

from sheep_tpu.analysis.core import (RULES, SEVERITY_RANK, load_baseline,
                                     write_baseline)
from sheep_tpu.analysis.runner import lint_paths

DEFAULT_BASELINE = "sheeplint_baseline.json"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="sheeplint",
        description="JAX-hazard static analyzer for the sheep-tpu "
                    "dispatch pipeline's invariants (rules: "
                    + ", ".join(sorted(RULES)) + ")")
    p.add_argument("paths", nargs="*", default=["sheep_tpu", "tools"],
                   help="files/directories to lint (default: "
                        "sheep_tpu tools)")
    p.add_argument("--check", action="store_true",
                   help="gate mode: same lint, nonzero exit on any "
                        "non-baselined finding (tier-1 spelling)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a JSON array")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        "next to the current directory when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file (show everything)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "file and exit 0 (the ratchet reset; review "
                        "the diff)")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="restrict to a comma-separated rule subset")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}: {RULES[rid]}")
        return 0

    # a mistyped/renamed path must fail loudly, not lint nothing and
    # report the gate green
    for p in args.paths:
        if not os.path.exists(p):
            print(f"sheeplint: no such path: {p}", file=sys.stderr)
            return 3
        if os.path.isfile(p) and not p.endswith(".py"):
            print(f"sheeplint: not a Python file: {p}", file=sys.stderr)
            return 3

    bl_path = args.baseline or DEFAULT_BASELINE
    baseline = set() if (args.no_baseline or args.write_baseline) \
        else load_baseline(bl_path)

    try:
        findings, baselined, parse_errors = lint_paths(args.paths, baseline)
    except OSError as e:
        print(f"sheeplint: {e}", file=sys.stderr)
        return 3

    if args.rules:
        keep = {r.strip() for r in args.rules.split(",")}
        # parse errors always survive the filter: an unparseable file
        # is unchecked by EVERY rule, not clean under one
        findings = [f for f in findings
                    if f.rule in keep or f.rule == "parse"]

    if args.write_baseline:
        write_baseline(bl_path, findings)
        print(f"sheeplint: wrote {len(findings)} finding(s) to {bl_path}")
        return 0

    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        for f in findings:
            print(f.render())
        n_err = sum(1 for f in findings if f.severity == "error")
        n_warn = len(findings) - n_err
        note = f" ({baselined} baselined)" if baselined else ""
        files = args.paths if isinstance(args.paths, list) else [args.paths]
        print(f"sheeplint: {n_err} error(s), {n_warn} warning(s)"
              f"{note} in {' '.join(files)}")

    if not findings:
        return 0
    worst = max(SEVERITY_RANK.get(f.severity, 2) for f in findings)
    return 1 if worst >= SEVERITY_RANK["error"] else 2


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
