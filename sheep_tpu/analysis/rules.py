"""The sheeplint rule classes (ISSUE 6; ``h2d`` added by ISSUE 12).

Each rule is an AST pass over one file, sharing the cross-file
:class:`~sheep_tpu.analysis.index.PackageIndex`. The analyses are
deliberately HEURISTIC — linear statement-order taint with no fixpoint,
no inter-procedural flow — tuned so that (a) every rule fires on the
canonical bad pattern it exists for (pinned by tests/test_sheeplint.py
fixtures) and (b) the current package audits clean without baselining,
with the legitimate sync points carrying ``# sheeplint: sync-ok``
pragmas that double as documentation and as the map of where the
runtime sanitizer's ``sync_ok()`` windows belong. A heuristic this
shape catches the regression that matters — someone inlining an
``int(sv[0])`` into a dispatch loop — without drowning the gate in
false positives that would teach people to sprinkle pragmas blindly.

Rules:

- **sync** — implicit device->host syncs: ``int()``/``float()``/
  ``bool()``/``.item()``/``.tolist()``/``np.asarray()`` applied to, or
  ``if``/``while``/``assert`` branching on, a value that flows from a
  jit'd call (or a ``jax.Array``-annotated parameter). One stray sync
  in the dispatch path reverts the in-flight pipeline to lockstep
  (PR 3's invariant).
- **donate** — use-after-donate: a variable passed at a donated
  position (``donate_argnums``, or any ``*_donated`` callee) is dead;
  reading it later is the live bug class
  ``fold_segments_batch_pos_donated`` introduced.
- **jit** — hygiene: jit construction inside a loop (recompilation per
  iteration), non-tuple ``static_argnums``/``static_argnames``
  literals, and Python branching on traced values inside a jit'd
  function (trace-time ConcretizationTypeError, or worse, silent
  specialization).
- **resource** — balance: a ``prefetch``/``prefetch_batched``/
  ``Prefetcher`` acquired without a guaranteed release (``with``,
  immediate ``return``, or a ``.close()`` on the name somewhere in the
  function), an ``obs.begin``/``.begin`` span with no ``.end()`` on
  any path, ``obs.span`` constructed outside a ``with``, and counter
  registries mutated by subscript instead of inc/gauge/absorb.
- **lock** — thread-shared state: in a class owning a
  ``threading.Lock``, attributes written under the lock somewhere must
  be written under it everywhere (the MetricsWriter/heartbeat
  precedent).
- **h2d** — blocking host->device staging on per-chunk hot paths:
  ``jnp.asarray``/``jnp.array``/``jax.device_put`` of a host value
  inside a loop (the synchronous-upload shape the staged H2D ring
  removed, ISSUE 12); designed windows carry ``# sheeplint: h2d-ok``.
- **fold** — the resident delta-fold path (ISSUE 19): inside a
  ``*fold_delta*``/``*move_rescore*`` function, constructing a fold
  pipeline or a jit (per-EPOCH recompile — the cached ``_update_pipe``
  / ``_MOVE_RESCORE_CACHE`` helpers exist so repeat epochs reuse every
  compiled program) and per-CHUNK host pulls inside a loop
  (``np.asarray``/``.item()``/``.tolist()``/``.block_until_ready()`` —
  the O(Δ) epoch's designed shape is ONE pull after the fold
  converges); designed windows carry ``# sheeplint: fold-ok``.
- **spill** — out-of-core discipline (ISSUE 20): full materialization
  of an mmap CSR region (``np.asarray``/``np.array`` over a bare
  ``indices``/``indptr`` attribute or a whole-region ``[:]`` slice of
  one — the disk tier pulled entirely into host memory, defeating the
  O(chunk) working-set contract; element/range subscripts stay
  O(slice) and are fine), and a per-chunk device upload inside a loop
  (``jax.device_put``/``jnp.asarray`` of a ``pad_chunk``/
  ``device_chunk`` product) that bypasses the residency manager — HBM
  the budget model cannot see or spill; designed windows carry
  ``# sheeplint: spill-ok``.
"""

from __future__ import annotations

import ast

from sheep_tpu.analysis.core import Finding, pragma_lines, suppressed
from sheep_tpu.analysis.index import PackageIndex, _jit_call_info

#: attribute reads that yield host metadata, not device values
METADATA_ATTRS = {
    "shape", "ndim", "dtype", "size", "nbytes", "itemsize", "sharding",
    "device", "devices", "is_deleted", "addressable_shards", "weak_type",
}

#: module roots whose calls produce device arrays
DEVICE_MODULES = {"jnp", "lax"}

#: receiver methods that fold a tainted argument into the receiver
CONTAINER_MUTATORS = {"append", "appendleft", "add", "insert", "extend",
                      "update", "put"}

HOST_CONVERTERS = {"int", "float", "bool", "complex"}

PREFETCH_FNS = {"prefetch", "prefetch_batched", "Prefetcher"}

LOCK_MUTATING_METHODS = {
    "write", "writelines", "flush", "close", "append", "extend",
    "insert", "pop", "popleft", "clear", "update", "add", "remove",
    "discard", "put", "emit", "send", "truncate", "seek",
}


def _terminal(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _root(node) -> str:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else ""


def _is_np_pull(call: ast.Call) -> bool:
    """np.asarray(x) / np.array(x) — the explicit pull form."""
    fn = call.func
    return (isinstance(fn, ast.Attribute) and fn.attr in ("asarray", "array")
            and _root(fn) in ("np", "numpy"))


class RuleContext:
    def __init__(self, path: str, source: str, tree: ast.Module,
                 index: PackageIndex):
        self.path = path
        self.tree = tree
        self.index = index
        self.pragmas = pragma_lines(source)
        self.findings: list = []

    def add(self, rule: str, severity: str, node, message: str) -> None:
        f = Finding(rule=rule, severity=severity, path=self.path,
                    line=getattr(node, "lineno", 0), message=message)
        span = (getattr(node, "lineno", 0),
                getattr(node, "end_lineno", None))
        if not suppressed(f, self.pragmas, span):
            self.findings.append(f)


def _decorated_jit(fn) -> tuple:
    """(is_jit, static_param_names) for a FunctionDef's decorators."""
    static: set = set()
    is_jit = False
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            ok, _ = _jit_call_info(dec)
            if not ok:
                continue
            is_jit = True
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
            for kw in dec.keywords:
                if kw.arg == "static_argnames" and \
                        isinstance(kw.value, (ast.Tuple, ast.List)):
                    static |= {e.value for e in kw.value.elts
                               if isinstance(e, ast.Constant)
                               and isinstance(e.value, str)}
                elif kw.arg == "static_argnums":
                    vals = kw.value.elts \
                        if isinstance(kw.value, (ast.Tuple, ast.List)) \
                        else [kw.value]
                    for e in vals:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, int) \
                                and e.value < len(params):
                            static.add(params[e.value])
        elif _terminal(dec) == "jit":
            is_jit = True
    return is_jit, static


# ---------------------------------------------------------------------------
# sync + jit-branching + donate: one linear taint pass per scope
# ---------------------------------------------------------------------------

class _TaintScope:
    """Linear statement-order taint over one function (or module) body.

    ``in_jit`` switches the sink rule: outside jit, a host conversion /
    branch on a tainted value is a **sync** finding; inside a jit'd
    function the same shape is a **jit** finding (it does not sync —
    it breaks or silently specializes the trace)."""

    def __init__(self, ctx: RuleContext, in_jit: bool = False,
                 taint=None, jit_aliases=None, donating_aliases=None):
        self.ctx = ctx
        self.in_jit = in_jit
        self.taint = set(taint or ())
        self.jit_aliases = set(jit_aliases or ())
        self.donating_aliases = set(donating_aliases or ())
        self.dead: dict = {}  # name -> donating callee (use-after-donate)
        # per-key taint for dicts built from literals with constant
        # string keys: the dispatch drivers keep mixed host/device
        # state in one dict ({"tipP": <device>, "flushing": False}),
        # and blanket container taint would flag every host-field read
        self.key_taint: dict = {}  # name -> set of tainted keys

    # -- callee classification ---------------------------------------------
    def _callee_jit(self, call: ast.Call) -> bool:
        name = _terminal(call.func)
        if name in self.jit_aliases or self.ctx.index.is_jit(name):
            return True
        return _root(call.func) in DEVICE_MODULES

    def _callee_donating(self, call: ast.Call):
        name = _terminal(call.func)
        if name in self.donating_aliases:
            return name, None
        if self.ctx.index.is_donating(name):
            return name, self.ctx.index.donated_positions(name)
        return None, ()

    # -- taint of an expression --------------------------------------------
    def tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if isinstance(node, ast.Attribute):
            if node.attr in METADATA_ATTRS:
                return False
            return self.tainted(node.value)
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in self.key_taint \
                    and isinstance(node.slice, ast.Constant):
                return node.slice.value in self.key_taint[node.value.id]
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in HOST_CONVERTERS:
                return False  # host converter launders (and is a sink)
            if _is_np_pull(node):
                return False
            if self._callee_jit(node):
                return True
            # a method on a tainted receiver stays device-side
            # (P.astype(...), table.at[...].min(...))
            if isinstance(fn, ast.Attribute) and self.tainted(fn):
                return True
            return False
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.tainted(node.left) or \
                any(self.tainted(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None and self.tainted(v)
                       for v in node.values)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.tainted(node.value)
        return False

    # -- sinks --------------------------------------------------------------
    def _sync_rule(self):
        return ("jit", "error") if self.in_jit else ("sync", "error")

    def scan(self, expr) -> None:
        """Flag sink patterns in one expression tree (nested function
        bodies excluded — they get their own scopes)."""
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Call):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in self.dead:
                    self.ctx.add(
                        "donate", "error", node,
                        f"'{node.id}' was donated to "
                        f"{self.dead[node.id]}() and is dead; reading "
                        f"it is use-after-donate (rebind it, or drop "
                        f"the donation)")
                    del self.dead[node.id]
                continue
            fn = node.func
            rule, sev = self._sync_rule()
            if isinstance(fn, ast.Name) and fn.id in HOST_CONVERTERS \
                    and len(node.args) == 1 \
                    and self.tainted(node.args[0]):
                self.ctx.add(
                    rule, sev, node,
                    f"{fn.id}() on a value from a jit'd call "
                    + ("inside a jit'd function (breaks or "
                       "specializes the trace)" if self.in_jit else
                       "forces an implicit device->host sync; pull "
                       "via np.asarray at an annotated sync point "
                       "(# sheeplint: sync-ok) or keep it a future"))
            elif _is_np_pull(node) and node.args \
                    and self.tainted(node.args[0]):
                self.ctx.add(
                    rule, sev, node,
                    "np.asarray/np.array on a value from a jit'd call "
                    "is a device->host pull; annotate the designed "
                    "sync point with '# sheeplint: sync-ok' (and wrap "
                    "it in sanitize.sync_ok() on guarded paths)")
            elif isinstance(fn, ast.Attribute) \
                    and fn.attr in ("item", "tolist") \
                    and self.tainted(fn.value):
                self.ctx.add(
                    rule, sev, node,
                    f".{fn.attr}() on a value from a jit'd call "
                    "forces an implicit device->host sync")

    # -- assignment ---------------------------------------------------------
    def _bind(self, target, is_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            self.dead.pop(target.id, None)
            self.key_taint.pop(target.id, None)
            if is_tainted:
                self.taint.add(target.id)
            else:
                self.taint.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, is_tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, is_tainted)
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Name) and base.id in self.key_taint \
                    and isinstance(target.slice, ast.Constant):
                if is_tainted:
                    self.key_taint[base.id].add(target.slice.value)
                else:
                    self.key_taint[base.id].discard(target.slice.value)
            elif is_tainted and isinstance(base, ast.Name):
                # storing a tainted value into a container taints it
                self.taint.add(base.id)

    def _track_dict(self, target, value) -> None:
        if isinstance(target, ast.Name) and isinstance(value, ast.Dict) \
                and all(isinstance(k, ast.Constant) for k in value.keys):
            self.key_taint[target.id] = {
                k.value for k, v in zip(value.keys, value.values)
                if v is not None and self.tainted(v)}

    def _mark_donated(self, call: ast.Call) -> None:
        name, positions = self._callee_donating(call)
        if name is None:
            return
        if any(isinstance(a, ast.Starred) for a in call.args):
            return  # positions unresolvable
        args = call.args
        idxs = range(len(args)) if positions is None else positions
        for i in idxs:
            if i < len(args) and isinstance(args[i], ast.Name):
                self.dead[args[i].id] = name

    def _maybe_alias(self, target, value) -> None:
        """``fold = donated_fn if cond else plain_fn`` — record the
        alias so calls through it keep jit/donate semantics."""
        names = []
        if isinstance(value, (ast.Name, ast.Attribute)):
            names = [_terminal(value)]
        elif isinstance(value, ast.IfExp):
            names = [_terminal(value.body), _terminal(value.orelse)]
        if not names or not isinstance(target, ast.Name):
            return
        if any(self.ctx.index.is_jit(n) for n in names if n):
            self.jit_aliases.add(target.id)
        if any(self.ctx.index.is_donating(n) for n in names if n):
            self.donating_aliases.add(target.id)

    # -- statements ---------------------------------------------------------
    def exec_body(self, stmts) -> None:
        for st in stmts:
            self.exec_stmt(st)

    def _donate_in(self, expr) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._mark_donated(node)

    def exec_stmt(self, st) -> None:
        ctx = self.ctx
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._run_nested(st)
            return
        if isinstance(st, ast.ClassDef):
            for sub in st.body:
                self.exec_stmt(sub)
            return
        if isinstance(st, ast.Assign):
            self.scan(st.value)
            self._donate_in(st.value)
            is_t = self.tainted(st.value)
            for tgt in st.targets:
                self._maybe_alias(tgt, st.value)
                self._bind(tgt, is_t)
                self._track_dict(tgt, st.value)
        elif isinstance(st, ast.AnnAssign):
            self.scan(st.value)
            self._donate_in(st.value)
            if st.value is not None:
                self._bind(st.target, self.tainted(st.value))
        elif isinstance(st, ast.AugAssign):
            self.scan(st.value)
            self._donate_in(st.value)
            if isinstance(st.target, ast.Name):
                if self.tainted(st.value):
                    self.taint.add(st.target.id)
        elif isinstance(st, ast.Expr):
            self.scan(st.value)
            self._donate_in(st.value)
            v = st.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
                # container.append(tainted, ...) taints the container
                if v.func.attr in CONTAINER_MUTATORS \
                        and isinstance(v.func.value, ast.Name) \
                        and any(self.tainted(a) for a in v.args):
                    self.taint.add(v.func.value.id)
        elif isinstance(st, (ast.Return, ast.Delete, ast.Raise)):
            for child in ast.iter_child_nodes(st):
                self.scan(child)
                self._donate_in(child)
        elif isinstance(st, ast.Assert):
            self.scan(st.test)
            if self.tainted(st.test):
                rule, sev = self._sync_rule()
                # anchor to the test expression, not the statement: the
                # statement's line span covers the whole body, so an
                # unrelated pragma inside it would suppress this finding
                ctx.add(rule, sev, st.test,
                        "assert on a value from a jit'd call "
                        + ("inside a jit'd function" if self.in_jit
                           else "forces an implicit device->host sync"))
        elif isinstance(st, (ast.If, ast.While)):
            self.scan(st.test)
            self._donate_in(st.test)
            if self.tainted(st.test):
                rule, sev = self._sync_rule()
                kw = "while" if isinstance(st, ast.While) else "if"
                ctx.add(rule, sev, st.test,  # test, not st: see Assert
                        f"Python `{kw}` on a value from a jit'd call "
                        + ("inside a jit'd function (trace-time "
                           "branch)" if self.in_jit else
                           "forces an implicit device->host sync; "
                           "read it at an annotated sync point first"))
            self.exec_body(st.body)
            self.exec_body(st.orelse)
        elif isinstance(st, ast.For):
            self.scan(st.iter)
            self._donate_in(st.iter)
            self._bind(st.target, self.tainted(st.iter))
            self.exec_body(st.body)
            self.exec_body(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self.scan(item.context_expr)
                self._donate_in(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self.tainted(item.context_expr))
            self.exec_body(st.body)
        elif isinstance(st, ast.Try):
            self.exec_body(st.body)
            for h in st.handlers:
                self.exec_body(h.body)
            self.exec_body(st.orelse)
            self.exec_body(st.finalbody)
        # Import / Global / Pass / Break / Continue: nothing to do

    def _run_nested(self, fn) -> None:
        is_jit, static = _decorated_jit(fn)
        sub = _TaintScope(
            self.ctx,
            in_jit=is_jit or self.in_jit,
            taint=self.taint,  # free-variable approximation
            jit_aliases=self.jit_aliases,
            donating_aliases=self.donating_aliases)
        sub.key_taint = {k: set(v) for k, v in self.key_taint.items()}
        if is_jit:
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args
                      + fn.args.kwonlyargs]
            sub.taint |= {p for p in params if p not in static}
        else:
            for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
                # jax.Array-annotated params are device values; np.ndarray
                # (and unannotated) params are host side
                ann = a.annotation
                if ann is not None and _terminal(ann) == "Array" \
                        and (isinstance(ann, ast.Name)
                             or _root(ann) in ("jax", "jnp")):
                    sub.taint.add(a.arg)
                else:
                    sub.taint.discard(a.arg)
        sub.exec_body(fn.body)


def check_sync_donate(ctx: RuleContext) -> None:
    scope = _TaintScope(ctx, in_jit=False)
    scope.exec_body(ctx.tree.body)


# ---------------------------------------------------------------------------
# jit hygiene: construction-in-loop + non-tuple static literals
# ---------------------------------------------------------------------------

class _JitHygiene(ast.NodeVisitor):
    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self.loop_depth = 0

    def _check_call(self, node: ast.Call) -> None:
        is_jit, _ = _jit_call_info(node)
        if not is_jit:
            return
        if self.loop_depth > 0:
            self.ctx.add(
                "jit", "warning", node,
                "jax.jit constructed inside a loop: every iteration "
                "builds (and likely recompiles) a fresh program — "
                "hoist it or cache per static key")
        for kw in node.keywords:
            if kw.arg in ("static_argnums", "static_argnames") \
                    and isinstance(kw.value, (ast.List, ast.Set,
                                              ast.Dict)):
                self.ctx.add(
                    "jit", "warning", kw.value,
                    f"{kw.arg} given a non-tuple literal; use a tuple "
                    "(hashable, order-stable) so the jit cache key is "
                    "well-defined")

    def visit_Call(self, node):
        self._check_call(node)
        self.generic_visit(node)

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = _loop
    visit_ListComp = visit_SetComp = visit_DictComp = _loop
    visit_GeneratorExp = _loop


def check_jit_hygiene(ctx: RuleContext) -> None:
    _JitHygiene(ctx).visit(ctx.tree)


# ---------------------------------------------------------------------------
# resource balance
# ---------------------------------------------------------------------------

def _collect_method_receivers(fn, method: str) -> set:
    """Names X with an ``X.<method>(...)`` call anywhere in fn's
    subtree (nested defs included — closures may release for the
    enclosing scope, e.g. the rolling dispatch spans)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == method \
                and isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def _assigned_names(target) -> list:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    return []


def _immediate_stmts(scope):
    """Statements of ``scope`` excluding nested function bodies (those
    are their own scopes; `with`-acquired resources never reach here
    because only Assign/Expr statements are classified)."""
    out: list = []

    def rec(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                continue
            out.append(st)
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(st, fld, None)
                if isinstance(sub, list):
                    rec(sub)
            for h in getattr(st, "handlers", ()):
                rec(h.body)

    rec(scope.body)
    return out


def check_resources(ctx: RuleContext) -> None:
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        # releases may live in nested closures acting for this scope
        # (the rolling dispatch spans), so collect over the full subtree
        closers = _collect_method_receivers(scope, "close")
        enders = _collect_method_receivers(scope, "end")
        for st in _immediate_stmts(scope):
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                name = _terminal(st.value.func)
                targets = []
                for t in st.targets:
                    targets.extend(_assigned_names(t))
                if name in PREFETCH_FNS:
                    if not any(t in closers for t in targets):
                        ctx.add(
                            "resource", "error", st.value,
                            f"{name}(...) bound to "
                            f"{'/'.join(targets) or 'a non-name target'}"
                            " with no close() on any path: an "
                            "abandoning consumer leaks the worker "
                            "thread — use `with ... as pf:` or "
                            "close() in a finally")
                elif name == "begin":
                    if not any(t in enders for t in targets):
                        ctx.add(
                            "resource", "error", st.value,
                            "span begun but never .end()ed in this "
                            "function: the trace reports it UNCLOSED "
                            "on every run, not just dead ones — end "
                            "it, or use `with obs.span(...)`")
            elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                name = _terminal(st.value.func)
                if name in PREFETCH_FNS:
                    ctx.add("resource", "error", st.value,
                            f"{name}(...) result discarded: the worker "
                            "thread starts and nothing can ever stop it")
                elif name == "begin":
                    ctx.add("resource", "error", st.value,
                            "span begun and discarded: nothing can "
                            "end it")
            if isinstance(st, (ast.Assign, ast.AugAssign)):
                tgts = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for tgt in tgts:
                    if isinstance(tgt, ast.Subscript) \
                            and isinstance(tgt.value, ast.Attribute) \
                            and tgt.value.attr == "counters":
                        ctx.add(
                            "resource", "warning", st,
                            "counters mutated by subscript outside "
                            "the CounterRegistry API; use inc()/"
                            "gauge()/absorb() so heartbeat snapshots "
                            "and span deltas stay consistent")


# ---------------------------------------------------------------------------
# h2d staging (ISSUE 12): blocking host->device uploads on per-chunk
# hot paths
# ---------------------------------------------------------------------------

class _H2DStaging(ast.NodeVisitor):
    """Flag ``jnp.asarray``/``jnp.array``/``jax.device_put`` calls
    lexically inside a ``for``/``while`` loop — the per-chunk hot-path
    shape whose synchronous H2D transfer the staged ring
    (utils/prefetch.H2DRing) replaced, and the regression class this
    rule keeps from creeping back. Device-valued arguments move no host
    bytes (a jnp call on a jnp/lax result is the *sync* rule's domain,
    not this one's), so the obvious ones are skipped; the designated
    windows — the ring's own issue point, per-attempt resume uploads,
    measurement probes — carry ``# sheeplint: h2d-ok``, the same
    reviewed-whitelist convention as ``sync-ok``."""

    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self.loop_depth = 0

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def _def(self, node):
        # a nested function's body does not execute per iteration of
        # the enclosing loop; it gets its own scan at depth 0
        depth, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = depth

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _def

    @staticmethod
    def _device_valued(arg) -> bool:
        return isinstance(arg, ast.Call) and _root(arg.func) in DEVICE_MODULES

    def visit_Call(self, node):
        if self.loop_depth > 0:
            fn = node.func
            if isinstance(fn, ast.Attribute):
                root = _root(fn)
                h2d = (fn.attr in ("asarray", "array") and root == "jnp") \
                    or (fn.attr == "device_put" and root == "jax")
                if h2d and node.args \
                        and not self._device_valued(node.args[0]):
                    self.ctx.add(
                        "h2d", "error", node,
                        f"{root}.{fn.attr}() inside a loop issues a "
                        "host->device transfer on the hot path at the "
                        "moment the value is needed — stage it ahead "
                        "through utils/prefetch.H2DRing (or a device "
                        "stream), or annotate a designed window with "
                        "'# sheeplint: h2d-ok'")
        self.generic_visit(node)


def check_h2d(ctx: RuleContext) -> None:
    _H2DStaging(ctx).visit(ctx.tree)


# ---------------------------------------------------------------------------
# delta fold path (ISSUE 19): per-epoch recompiles and per-chunk host
# syncs in the resident update fold
# ---------------------------------------------------------------------------

#: the multi-device fold pipelines — constructing one compiles programs
FOLD_PIPELINE_CTORS = {"ShardedPipeline", "BigVPipeline"}

#: method pulls that synchronize device work onto the host
FOLD_PULL_METHODS = {"item", "tolist", "block_until_ready"}


class _FoldPath(ast.NodeVisitor):
    """Scan ``*fold_delta*`` / ``*move_rescore*`` function bodies — the
    per-epoch resident update path (ISSUE 19). Two regression classes:

    - a fold pipeline constructed (or a jit built) inline re-COMPILES
      every epoch; the epoch cost then is compile wall, not the O(Δ)
      fold — the cached ``_update_pipe`` / ``_MOVE_RESCORE_CACHE``
      helpers are the blessed shape;
    - a host pull (``np.asarray``/``.item()``/``.tolist()``/
      ``.block_until_ready()``) inside a chunk loop serializes the
      lockstep fold per chunk; the designed shape pulls ONCE after the
      fold converges (those single pulls sit at loop depth 0, or carry
      ``# sheeplint: fold-ok``)."""

    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self.loop_depth = 0
        self.in_fold = False

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def _def(self, node):
        name = getattr(node, "name", "")
        # _make_* builders are the cached-construction fix this rule
        # recommends — the one place a compile belongs
        on_path = ("fold_delta" in name or "move_rescore" in name) \
            and not name.startswith("_make")
        fold, self.in_fold = self.in_fold, self.in_fold or on_path
        # a nested function's body does not execute per iteration of
        # the enclosing loop; it gets its own scan at depth 0
        depth, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = depth
        self.in_fold = fold

    visit_FunctionDef = visit_AsyncFunctionDef = _def

    def visit_Lambda(self, node):
        depth, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = depth

    def visit_Call(self, node):
        if self.in_fold:
            term = _terminal(node.func)
            is_jit, _ = _jit_call_info(node)
            if term in FOLD_PIPELINE_CTORS or is_jit:
                self.ctx.add(
                    "fold", "error", node,
                    f"{term}(...) constructed on the delta fold path: "
                    "every epoch recompiles its programs — build it "
                    "once in a cached helper (the _update_pipe "
                    "convention), or annotate a designed window with "
                    "'# sheeplint: fold-ok'")
            elif self.loop_depth > 0:
                pull = _is_np_pull(node) or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in FOLD_PULL_METHODS)
                if pull:
                    self.ctx.add(
                        "fold", "error", node,
                        "host pull inside a loop on the delta fold "
                        "path serializes the lockstep fold per chunk "
                        "— pull ONCE after the fold converges, or "
                        "annotate a designed window with "
                        "'# sheeplint: fold-ok'")
        self.generic_visit(node)


def check_fold(ctx: RuleContext) -> None:
    _FoldPath(ctx).visit(ctx.tree)


# ---------------------------------------------------------------------------
# out-of-core residency discipline (ISSUE 20)
# ---------------------------------------------------------------------------

#: mmap-backed CSR region attributes (io/csr.py CsrView)
CSR_REGION_ATTRS = {"indices", "indptr", "_indices", "_indptr"}

#: chunk producers whose result is the residency plane's unit
CHUNK_PRODUCERS = {"pad_chunk", "device_chunk", "device_chunk_on"}


def _full_region_pull(arg) -> str:
    """The CSR region attribute ``arg`` fully materializes, or ''.
    Full = the bare attribute (``view._indices``) or a whole-region
    slice of it (``view._indices[:]``). An element/range subscript
    (``self._indices[eid]``) reads only the rows asked for — O(slice),
    exactly the mmap contract — and is not flagged."""
    if isinstance(arg, ast.Subscript):
        sl = arg.slice
        if not (isinstance(sl, ast.Slice) and sl.lower is None
                and sl.upper is None and sl.step is None):
            return ""
        arg = arg.value
    if isinstance(arg, ast.Attribute) and arg.attr in CSR_REGION_ATTRS:
        return arg.attr
    return ""


def _chunk_valued(arg) -> bool:
    """True when ``arg`` is recognizably a streamed chunk: a
    ``pad_chunk``/``device_chunk`` call, or a name whose terminal
    mentions 'chunk' (the drivers' naming convention)."""
    if isinstance(arg, ast.Call):
        return _terminal(arg.func) in CHUNK_PRODUCERS
    return "chunk" in _terminal(arg).lower()


class _SpillPath(ast.NodeVisitor):
    """The two regression classes the out-of-core plane (ISSUE 20)
    creates room for:

    - ``np.asarray``/``np.array`` over a whole mmap CSR region pulls
      the DISK tier entirely into host memory — the working set is
      back to O(E) and the budget means nothing;
    - a per-chunk ``jax.device_put``/``jnp.asarray`` upload inside a
      loop puts evictable chunk bytes on device OUTSIDE the residency
      manager: HBM the budget model cannot account, spill, or evict at
      a checkpoint boundary (the blessed paths go through
      ``_residency_chunks``/``admit`` or the staged H2D ring).

    Designed windows (the refine re-stream, device-synth placement
    relays) carry ``# sheeplint: spill-ok``."""

    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self.loop_depth = 0

    def _loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def _def(self, node):
        # a nested function's body does not execute per iteration of
        # the enclosing loop; it gets its own scan at depth 0
        depth, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = depth

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _def

    def visit_Call(self, node):
        if _is_np_pull(node) and node.args:
            region = _full_region_pull(node.args[0])
            if region:
                self.ctx.add(
                    "spill", "error", node,
                    f"np.{_terminal(node.func)}() over the whole "
                    f"'{region}' mmap region materializes the disk "
                    "tier into host memory — slice the rows you need "
                    "(the view stays O(slice)), or annotate a designed "
                    "window with '# sheeplint: spill-ok'")
        elif self.loop_depth > 0 and node.args:
            term = _terminal(node.func)
            root = _root(node.func)
            uploader = term == "device_put" or (
                term in ("asarray", "array") and root in ("jnp", "jax"))
            if uploader and _chunk_valued(node.args[0]):
                self.ctx.add(
                    "spill", "error", node,
                    f"{root}.{term}() of a chunk inside a loop puts "
                    "evictable bytes on device outside the residency "
                    "manager — HBM the budget cannot account or spill; "
                    "serve chunks through the residency/H2D staging "
                    "path, or annotate a designed window with "
                    "'# sheeplint: spill-ok'")
        self.generic_visit(node)


def check_spill(ctx: RuleContext) -> None:
    _SpillPath(ctx).visit(ctx.tree)


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------

def _self_attr(node, names=None):
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        if names is None or node.attr in names:
            return node.attr
    return None


def _lock_writes(node, lock_attrs, under_lock, out) -> None:
    """Collect (attr, node, under_lock) for self-attribute writes and
    mutating method calls, tracking `with self.<lock>:` nesting."""
    if isinstance(node, ast.With):
        locked = under_lock or any(
            _self_attr(i.context_expr, lock_attrs) for i in node.items)
        for sub in node.body:
            _lock_writes(sub, lock_attrs, locked, out)
        return
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            attr = _self_attr(t)
            if attr:
                out.append((attr, node, under_lock))
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.Call) \
                and isinstance(child.func, ast.Attribute) \
                and child.func.attr in LOCK_MUTATING_METHODS:
            attr = _self_attr(child.func.value)
            if attr:
                out.append((attr, child, under_lock))
        _lock_writes(child, lock_attrs, under_lock, out)


def check_locks(ctx: RuleContext) -> None:
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        lock_attrs = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _terminal(node.value.func) in ("Lock", "RLock"):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        lock_attrs.add(attr)
        if not lock_attrs:
            continue
        writes: list = []
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and meth.name != "__init__":
                for st in meth.body:
                    _lock_writes(st, lock_attrs, False, writes)
        protected = {a for a, _, locked in writes if locked} - lock_attrs
        for attr, node, locked in writes:
            if attr in protected and not locked:
                ctx.add(
                    "lock", "error", node,
                    f"self.{attr} is written under "
                    f"{'/'.join('self.' + a for a in sorted(lock_attrs))} "
                    "elsewhere but mutated here without it — a racing "
                    "thread (heartbeat/prefetch worker) can interleave")


# ---------------------------------------------------------------------------

ALL_CHECKS = (check_sync_donate, check_jit_hygiene, check_resources,
              check_locks, check_h2d, check_fold, check_spill)


def check_file(path: str, source: str, tree: ast.Module,
               index: PackageIndex) -> list:
    ctx = RuleContext(path, source, tree, index)
    for chk in ALL_CHECKS:
        chk(ctx)
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings
