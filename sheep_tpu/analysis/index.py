"""Cross-file pre-pass: which names are jit'd, and which donate.

The sync and donate rules need to know, at a call site, whether the
callee (a) returns device arrays (its results are unread futures the
host must not implicitly sync on) and (b) donates argument buffers
(its inputs are poisoned by the call). Both facts live at the callee's
DEFINITION — usually in another file — so the linter runs one indexing
pass over every file first and shares the result with all rules.

Indexed forms:

- ``@jax.jit`` / ``@partial(jax.jit, ...)`` / ``@jit`` decorated
  functions -> jit set (by bare name; call sites match on the terminal
  attribute, so ``elim_ops.fold_segment_pos`` resolves).
- ``name = jax.jit(f, ...)`` / ``self.attr = jax.jit(f, ...)``
  assignments -> jit set (by target's terminal name).
- any of the above carrying ``donate_argnums=(...)`` -> donating map
  name -> tuple of donated positions. A callee whose name ends in
  ``_donated`` is treated as donating even when its definition was not
  seen (the package's naming convention for donating twins); unknown
  positions poison every positional argument.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

DONATED_SUFFIX = "_donated"


@dataclass
class PackageIndex:
    jit_names: set = field(default_factory=set)
    donating: dict = field(default_factory=dict)  # name -> positions|None

    def is_jit(self, name: str) -> bool:
        return name in self.jit_names or self.is_donating(name)

    def is_donating(self, name: str) -> bool:
        return name in self.donating or name.endswith(DONATED_SUFFIX)

    def donated_positions(self, name: str):
        """Donated positional indices, or None for "all positionals"."""
        return self.donating.get(name)


def _terminal_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _jit_call_info(call: ast.Call):
    """(is_jit_construction, donate_positions|None|()) for a Call node.

    Recognizes ``jax.jit(...)``, bare ``jit(...)`` and
    ``partial(jax.jit, ...)``; donate positions come from a literal
    ``donate_argnums`` tuple/int when present (() = none seen)."""
    fn = call.func
    name = _terminal_name(fn)
    is_jit = name == "jit"
    if name == "partial" and call.args:
        inner = _terminal_name(call.args[0])
        if inner == "jit":
            is_jit = True
    if not is_jit:
        return False, ()
    donate = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            donate = _literal_positions(kw.value)
    return True, donate


def _literal_positions(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return None  # dynamic expression: positions unknown


class _Indexer(ast.NodeVisitor):
    def __init__(self, index: PackageIndex):
        self.index = index

    def visit_FunctionDef(self, node):
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                is_jit, donate = _jit_call_info(dec)
            elif _terminal_name(dec) == "jit":
                is_jit, donate = True, ()
            else:
                continue
            if is_jit:
                self.index.jit_names.add(node.name)
                if donate is None or donate:
                    self.index.donating[node.name] = donate
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call):
            is_jit, donate = _jit_call_info(node.value)
            if is_jit:
                for tgt in node.targets:
                    name = _terminal_name(tgt)
                    if name:
                        self.index.jit_names.add(name)
                        if donate is None or donate:
                            self.index.donating[name] = donate
        self.generic_visit(node)


def build_index(trees) -> PackageIndex:
    """``trees`` = iterable of parsed ``ast.Module`` objects."""
    index = PackageIndex()
    v = _Indexer(index)
    for tree in trees:
        v.visit(tree)
    return index
