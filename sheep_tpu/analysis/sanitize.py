"""Runtime sanitizer mode (``SHEEP_SANITIZE=1``) — the executable twin
of the sheeplint static rules.

Three checks, all free when the env var is unset:

- **stray-sync traps**: :func:`guard` arms, for the current thread, a
  region in which any *implicit* device->host conversion of a
  jax.Array (``int()``/``float()``/``bool()``/``__index__``/
  ``.item()``/``.tolist()``) raises :class:`SanitizeError` unless it
  happens inside a :func:`sync_ok` window — the runtime form of the
  ``# sheeplint: sync-ok`` pragma. The backends arm it around the
  fold/dispatch paths, so the invariant "stats words stay unread
  futures except at the annotated one-behind pulls" is enforced, not
  hoped for. Mechanics: the ArrayImpl conversion dunders are wrapped
  once (first armed guard), with a thread-local armed/sync depth pair;
  on real accelerators ``jax.transfer_guard_device_to_host`` is
  layered on top (it catches paths the dunder wrap cannot, e.g.
  ``__array__``), while on cpu-jax the guard never fires — device
  memory IS host memory, there is no transfer — which is exactly why
  the dunder traps exist: they make the sanitizer testable in CI.
  ``np.asarray`` is deliberately NOT trapped: it is the explicit pull
  form (JAX's own transfer-guard taxonomy calls it an explicit
  transfer), and the static sync rule already requires it to sit on a
  pragma-annotated line.
- **donation poisoning**: :func:`check_donated` asserts buffers passed
  at donated positions really were invalidated (``is_deleted``), so a
  platform silently ignoring donation — doubling HBM and keeping
  stale-read bugs latent — fails loudly; reading a poisoned buffer
  afterwards raises in jax itself.
- **span balance**: the tracer counts open spans; under sanitize mode
  ``Tracer.close()`` raises when any span was begun but never ended
  (obs/tracer.py), turning a leaked span from a forensic curiosity
  into a test failure.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

_TLS = threading.local()
_PATCH_LOCK = threading.Lock()
_PATCHED = False

#: conversion dunders that implicitly sync (method name -> human name)
_TRAP_METHODS = ("__bool__", "__int__", "__float__", "__index__",
                 "__complex__", "item", "tolist")


class SanitizeError(RuntimeError):
    """An armed sanitizer invariant was violated."""


def enabled() -> bool:
    return os.environ.get("SHEEP_SANITIZE", "") not in ("", "0")


def _depth(attr: str) -> int:
    return getattr(_TLS, attr, 0)


def in_sync_window() -> bool:
    return _depth("sync") > 0


def _trap(orig, name):
    def wrapper(self, *a, **kw):
        if _depth("armed") > 0 and _depth("sync") == 0:
            raise SanitizeError(
                f"implicit device->host sync via {name} inside a "
                f"sanitized fold/dispatch region; read device values "
                f"only at annotated sync points (wrap the pull in "
                f"sanitize.sync_ok() and mark the line "
                f"'# sheeplint: sync-ok')")
        return orig(self, *a, **kw)
    wrapper.__name__ = name
    wrapper._sheep_sanitize_orig = orig
    return wrapper


def _install_traps() -> None:
    """Wrap the ArrayImpl conversion dunders once per process. The
    wrappers are inert (two thread-local reads) outside armed regions,
    so installation is a one-way, low-cost switch."""
    global _PATCHED
    with _PATCH_LOCK:
        if _PATCHED:
            return
        from jax._src import array as _jarray

        cls = _jarray.ArrayImpl
        for name in _TRAP_METHODS:
            orig = getattr(cls, name, None)
            if orig is None or hasattr(orig, "_sheep_sanitize_orig"):
                continue
            try:
                setattr(cls, name, _trap(orig, name))
            except (AttributeError, TypeError):
                # an unpatchable method (C-level slot): the transfer
                # guard still covers it on real accelerators
                continue
        _PATCHED = True


def _transfer_guard(level: str):
    """``jax.transfer_guard_device_to_host(level)`` when available."""
    try:
        import jax

        return jax.transfer_guard_device_to_host(level)
    except Exception:
        from contextlib import nullcontext

        return nullcontext()


@contextmanager
def guard(region: str = "dispatch"):
    """Arm the stray-sync sanitizer for the calling thread while the
    ``with`` body runs. No-op (one env read) when sanitize mode is
    off; nests freely; other threads (prefetch workers, host-tail
    executors, heartbeat) are unaffected."""
    if not enabled():
        yield
        return
    _install_traps()
    _TLS.armed = _depth("armed") + 1
    try:
        with _transfer_guard("disallow"):
            yield
    finally:
        _TLS.armed = _depth("armed") - 1


@contextmanager
def sync_ok(label: str = ""):
    """An annotated sync point: implicit conversions are allowed for
    the calling thread while the body runs (the runtime twin of the
    ``# sheeplint: sync-ok`` pragma)."""
    if not enabled():
        yield
        return
    _TLS.sync = _depth("sync") + 1
    try:
        with _transfer_guard("allow"):
            yield
    finally:
        _TLS.sync = _depth("sync") - 1


def check_donated(*arrays, origin: str = "donated call") -> None:
    """Assert every array really was invalidated by a donating call.

    jax deletes donated inputs at the API layer on every backend, so a
    live (non-deleted) buffer here means the donation contract was
    dropped somewhere — the caller would silently double HBM and could
    read stale data without the use-after-donate error that makes the
    bug findable. No-op when sanitize mode is off or for non-jax
    values (numpy inputs are never donated)."""
    if not enabled():
        return
    for i, a in enumerate(arrays):
        deleted = getattr(a, "is_deleted", None)
        if deleted is not None and not deleted():
            raise SanitizeError(
                f"buffer {i} passed to {origin} at a donated position "
                f"was not invalidated — donation silently ignored "
                f"(double HBM) or a non-donating twin was called on "
                f"the donating path")
