"""sheeplint core: findings, pragma suppression, baseline ratchet.

A finding is (rule, severity, path, line, message). Suppression is
two-tier, mirroring the clang-tidy/NOLINT workflow:

- **pragma** — ``# sheeplint: <rule>-ok`` (or the blanket
  ``# sheeplint: ok``) anywhere on the physical lines a flagged node
  spans. Pragmas are the *reviewed whitelist*: at a legitimate sync
  point the same annotation that silences the static rule documents
  the design decision in place, and the runtime sanitizer's
  ``sanitize.sync_ok()`` is its executable twin.
- **baseline** — ``sheeplint_baseline.json``, a reviewed list of
  known findings keyed by (rule, path, line). The gate passes at zero
  *non-baselined* findings, so the check lands green on day one and
  only ever ratchets: new violations fail, fixed ones are removed
  from the file (``--write-baseline`` regenerates it).
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass

#: rule id -> one-line description (the catalog the CLI prints)
RULES = {
    "sync": "implicit device->host sync on a value flowing from a "
            "jit'd call (int/float/bool/.item()/np.asarray/branch) "
            "outside an annotated sync point",
    "donate": "read of a buffer after it was passed at a donated "
              "argument position (use-after-donate)",
    "jit": "jit hygiene: jit construction inside a loop, non-tuple "
           "static_argnums/static_argnames, Python branching on "
           "traced values inside a jit'd function",
    "resource": "resource balance: Prefetcher without a guaranteed "
                "close, span begun without an end, counters mutated "
                "outside a CounterRegistry",
    "lock": "thread-shared attribute written outside the owning lock",
    "h2d": "blocking host->device staging (jnp.asarray/jnp.array/"
           "jax.device_put of a host value) inside a loop — the "
           "per-chunk hot-path shape the staged H2D ring exists to "
           "replace; stage through utils/prefetch.H2DRing or annotate "
           "a designed window with '# sheeplint: h2d-ok'",
}

SEVERITY_RANK = {"error": 2, "warning": 1}


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str  # "error" | "warning"
    path: str
    line: int
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")

    def to_json(self) -> dict:
        return asdict(self)

    def baseline_key(self) -> tuple:
        return (self.rule, self.path, self.line)


# rule ids may carry digits (h2d), so the token class is [a-z0-9-]
_PRAGMA_RE = re.compile(
    r"#\s*sheeplint:\s*([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)")


def pragma_lines(source: str) -> dict:
    """line number -> set of suppressed rule ids ("*" = all rules).

    ``# sheeplint: sync-ok`` suppresses the sync rule on that line;
    ``# sheeplint: ok`` suppresses every rule. Several rules may be
    listed comma-separated (``# sheeplint: sync-ok, donate-ok``)."""
    out: dict = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        rules = set()
        for tok in m.group(1).split(","):
            tok = tok.strip()
            if tok == "ok":
                rules.add("*")
            elif tok.endswith("-ok"):
                rules.add(tok[:-3])
        if rules:
            out[i] = rules
    return out


def suppressed(finding: Finding, pragmas: dict, span: tuple) -> bool:
    """True when any physical line of the flagged node (``span`` =
    (lineno, end_lineno)) carries a pragma for this rule."""
    lo, hi = span
    for ln in range(lo, (hi or lo) + 1):
        rules = pragmas.get(ln)
        if rules and ("*" in rules or finding.rule in rules):
            return True
    return False


def load_baseline(path: str) -> set:
    """Baseline file -> set of (rule, path, line) keys. A missing file
    is an empty baseline (the gate starts strict)."""
    try:
        with open(path) as fh:
            entries = json.load(fh)
    except FileNotFoundError:
        return set()
    return {(e["rule"], e["path"], int(e["line"])) for e in entries}


def write_baseline(path: str, findings) -> None:
    entries = sorted(
        ({"rule": f.rule, "path": f.path, "line": f.line,
          "message": f.message} for f in findings),
        key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w") as fh:
        json.dump(entries, fh, indent=1)
        fh.write("\n")
