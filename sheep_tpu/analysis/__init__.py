"""sheep_tpu.analysis — sheeplint: the JAX-hazard static analyzer and
runtime sanitizer gate (ISSUE 6 tentpole).

PRs 1-3 made the dispatch pipeline fast by leaning on invariants that
nothing enforced: stats words must stay unread futures (one stray
``int(x)`` on a device array reverts the in-flight pipeline to
lockstep), donated tables must never be read after the call, the
elimination fixpoint's order-independence argument requires fold
kernels free of host-visible side effects, prefetch workers and spans
must be released on every abandonment path, and thread-shared sinks
must be written under their lock. This package turns those invariants
into machine checks:

- **static**: :func:`sheep_tpu.analysis.runner.lint_paths` runs five
  AST rule classes (sync / donate / jit / resource / lock — see
  ``rules.py``) over the package, with per-line pragma suppression
  (``# sheeplint: <rule>-ok``) and a reviewed ratchet baseline
  (``sheeplint_baseline.json``). CLI: ``tools/sheeplint.py`` /
  the ``sheeplint`` console script.
- **runtime**: :mod:`sheep_tpu.analysis.sanitize` arms (under
  ``SHEEP_SANITIZE=1``) implicit device->host conversion traps +
  ``jax.transfer_guard`` around the fold/dispatch paths, donation
  poisoning checks, and tracer span-balance assertions at close.
"""

from sheep_tpu.analysis.core import (Finding, RULES,  # noqa: F401
                                     load_baseline, write_baseline)
from sheep_tpu.analysis.runner import lint_paths, lint_source  # noqa: F401
