"""Hierarchical partitioning: k = k1 * k2 * ... via recursive refinement.

Motivated by a measured law (BASELINE.md "SBM quality"): label-
propagation refinement recovers community structure only while average
intra-community degree / k >= ~1 — at the LiveJournal shape it recovers
k=8 to near-optimal but stalls at k=64 (majority signal below noise).
Splitting k into levels keeps EVERY level above the signal threshold:
partition + refine at k1, then partition each part's induced subgraph
at the remaining levels (recursively), labeling vertex v as
part(v) * prod(k_rest) + subpart(v). Measured effect at the stalled
config (s22, 64 planted blocks, k=64): flat refine stalls at 0.847;
hierarchical [8, 8] — see BASELINE.md "SBM quality".

An EXTENSION beyond the reference's surface, like ops/refine.py; the
flat pipeline and every parity artifact are untouched.

Memory envelope: each level materializes each part's INTRA-part edges
(cross edges are already cut and never revisited), so host memory is
O(E_intra) = (1 - cut_so_far) * E for the bucketing pass plus one
subgraph at a time. Streams too big for that should partition flat
(the flat split has no such limit; this utility exists for cut QUALITY
on community-structured graphs).
"""

from __future__ import annotations

import numpy as np


def _hier_assign(stream, k_levels, backend, refine, chunk_edges,
                 opts):
    """Assignment over ``stream`` at k = prod(k_levels), recursing."""
    from sheep_tpu import _partition_stream
    from sheep_tpu.io.edgestream import EdgeStream

    n = stream.num_vertices
    # comm volume of inner levels is discarded (the final full-stream
    # score recomputes it once); chunk_edges forwards as the backends'
    # ctor option so the user's memory ceiling applies at every level
    res = _partition_stream(stream, k_levels[0], backend=backend,
                            refine=refine, chunk_edges=chunk_edges,
                            **{**opts, "comm_volume": False})
    assign = np.asarray(res.assignment, np.int64)
    if len(k_levels) == 1:
        return assign.astype(np.int32)

    k1 = k_levels[0]
    k_sub = int(np.prod(k_levels[1:]))
    # one bucketing pass: intra-part edges per part (cross edges are
    # final cut at this level and never revisited)
    buckets: list[list[np.ndarray]] = [[] for _ in range(k1)]
    for c in stream.chunks(chunk_edges):
        e = np.asarray(c, np.int64).reshape(-1, 2)
        pu = assign[e[:, 0]]
        same = pu == assign[e[:, 1]]
        for p in range(k1):
            m = same & (pu == p)
            if m.any():
                buckets[p].append(e[m])

    final = np.empty(n, np.int32)
    for p in range(k1):
        members = np.flatnonzero(assign == p)
        if len(members) == 0:
            continue
        if len(members) <= k_sub:
            # degenerate tiny part: round-robin so every vertex keeps a
            # valid label in [0, k_sub)
            final[members] = p * k_sub + np.arange(len(members)) % k_sub
            continue
        inv = np.full(n, -1, np.int64)       # dense relabel of the part
        inv[members] = np.arange(len(members))
        eb = (np.concatenate(buckets[p])
              if buckets[p] else np.empty((0, 2), np.int64))
        buckets[p] = []  # release the fragments as the loop advances
        sub_edges = inv[eb] if len(eb) else eb
        sub = EdgeStream.from_array(sub_edges, n_vertices=len(members))
        sub_assign = _hier_assign(sub, k_levels[1:], backend, refine,
                                  chunk_edges, opts)
        final[members] = p * k_sub + sub_assign
    return final


def partition_hierarchical(path, k_levels, backend=None, refine=8,
                           chunk_edges: int = 1 << 22, **opts):
    """Partition into prod(k_levels) parts, one level at a time.

    ``k_levels`` — e.g. ``[8, 8]`` for k=64. ``refine`` rounds apply at
    EVERY level (that is the point: each level stays above the LP
    signal threshold). Extra ``opts`` are the usual backend/partition
    options of :func:`sheep_tpu.partition`. Returns a PartitionResult
    scored over the full stream at k = prod(k_levels); ``backend``
    in the result is tagged ``+hier``.
    """
    from sheep_tpu.backends.base import score_stream
    from sheep_tpu.io.edgestream import open_input

    from sheep_tpu import _resolve_backend

    k_levels = [int(k) for k in k_levels]
    if len(k_levels) < 1 or any(k < 1 for k in k_levels):
        raise ValueError(f"k_levels must be positive ints, got {k_levels}")
    k_total = int(np.prod(k_levels))
    comm_volume = opts.get("comm_volume", True)
    inner_backend = _resolve_backend(backend, {})[0].name

    with open_input(path) as es:
        final = _hier_assign(es, k_levels, backend, refine, chunk_edges,
                             dict(opts))
        w = None
        if opts.get("weights") == "degree":
            # score with the same weights the levels balanced against,
            # like partition()/partition_multi
            n = es.num_vertices
            w = np.zeros(n, dtype=np.int64)
            for c in es.chunks(chunk_edges):
                w += np.bincount(np.asarray(c, np.int64).ravel(),
                                 minlength=n)[:n]
        scored = score_stream(es, {k_total: final},
                              chunk_edges=chunk_edges,
                              comm_volume=comm_volume, weights=w)
    cut, total, balance, cv = scored[k_total]
    from sheep_tpu.types import PartitionResult

    return PartitionResult(
        assignment=final, k=k_total, edge_cut=cut, total_edges=total,
        cut_ratio=cut / max(total, 1), balance=balance, comm_volume=cv,
        phase_times={}, backend=f"{inner_backend}+hier{k_levels}",
        diagnostics={})
