"""Hierarchical partitioning: k = k1 * k2 * ... via recursive refinement.

Motivated by a measured law (BASELINE.md "SBM quality"): label-
propagation refinement recovers community structure only while average
intra-community degree / k >= ~1 — at the LiveJournal shape it recovers
k=8 to near-optimal but stalls at k=64 (majority signal below noise).
Splitting k into levels keeps EVERY level above the signal threshold:
partition + refine at k1, then partition each part's induced subgraph
at the remaining levels (recursively), labeling vertex v as
part(v) * prod(k_rest) + subpart(v). Measured effect at the stalled
config (s22, 64 planted blocks, k=64): flat refine stalls at 0.847;
hierarchical [8, 8] — see BASELINE.md "SBM quality".

An EXTENSION beyond the reference's surface, like ops/refine.py; the
flat pipeline and every parity artifact are untouched.

Memory envelope (round 5, VERDICT r4 item 4): the level-1 bucketing
SPILLS each part's intra edges to a per-part ``.bin32`` temp shard in
one streaming pass — each chunk is grouped by part once (stable argsort
+ one boundary scan, not the old O(k1 * E) per-part mask pass — ADVICE
r4) and written relabeled, so host memory is O(V + chunk) regardless of
stream size and each induced subgraph is itself a file-backed stream.
Disk high-water mark is 8 bytes per intra edge of the current level.

Balance budgeting (VERDICT r4 item 4): pass ``balance=BETA`` to budget
the end-to-end bound as beta_level = BETA**(1/levels) per level (each
level's max-load factor multiplies, so per-level bounds compound to
~BETA); per-level refine caps are clamped to the same budget.

Level-1 leakage repair (VERDICT r4 item 3): ``final_refine=N`` runs N
rounds of capacity-constrained LP at the FULL k with the hierarchical
labels as warm start. The LP signal law objects to COLD starts at
k >= 64 (per-part majority ~ intra_degree/k is tie-noise); a warm start
only needs boundary repair, where the majority signal is local and
strong.

Production survival (ISSUE 8): hierarchy is a full member of the
checkpoint contract. Pass ``checkpointer=``/``resume=`` and the run
recovers at BOTH granularities: chunk-level inside level 0 (an ordinary
flat partition, checkpointed by the backend into the nested ``level0/``
domain) and level-boundary for the recursion (phase ``hier``: the
level-0 result, the partial final assignment, and the spill-file
manifest — each completed part advances the queue position, and the
per-part ``.bin32`` shards persist under the checkpoint dir so a
resumed run REUSES them instead of re-streaming the graph). A resumed
run is bit-identical to an uninterrupted one: level-0 restart is the
flat backends' proven mergeable-state property, and everything after
level 0 is a deterministic function of the level-0 assignment and the
spilled shards. Fault drills target the new granularities via
``SHEEP_FAULT_INJECT=level0:N`` / ``level:i`` (utils/fault.py).
"""

from __future__ import annotations

import glob
import os
import shutil
import tempfile
from contextlib import nullcontext

import numpy as np

from sheep_tpu import obs


_SPILL_MAX_FDS = 64


def level_ledger(stream, final, k_levels, edge_cut: int, total: int,
                 chunk_edges: int = 1 << 22) -> list:
    """Per-level cut attribution of a hierarchical assignment — the cut
    LEDGER (ISSUE 13): row d counts the edges whose endpoint labels
    first diverge at level d (level 0 = between top-level parts: the
    FRAGMENTATION term; level d > 0 = inside one level-(d-1) part but
    between its subparts: the MISASSIGNMENT terms). Rows sum exactly to
    the final edge cut, so "where does the residual to planted live" is
    answerable per level instead of as one opaque number.

    Computed with one extra stream pass scoring the level-PREFIX label
    projections ``final // prod(k_levels[d+1:])`` (cut-only, no comm
    volume) — the deepest prefix is the final assignment itself, whose
    cut the caller already holds. Levels with k = 1 contribute nothing
    and are folded into their parent row."""
    from sheep_tpu.backends.base import score_stream

    rows = []
    to_score = {}
    kp = 1
    suffix = int(np.prod(k_levels))
    for d, kd in enumerate(k_levels):
        kp *= int(kd)
        suffix //= int(kd)
        if kd <= 1:
            continue
        rows.append({"level": d, "k": kp})
        if suffix > 1:  # the deepest prefix IS final: cut known
            to_score[kp] = (np.asarray(final, np.int64)
                            // suffix).astype(np.int32)
    if not rows:
        rows = [{"level": 0, "k": kp}]
    if to_score:
        scored = score_stream(stream, to_score, chunk_edges=chunk_edges,
                              comm_volume=False)
        cum = {k: scored[k][0] for k in to_score}
    else:
        cum = {}
    cum[kp] = int(edge_cut)
    prev = 0
    for row in rows:
        c = int(cum.get(row["k"], edge_cut))
        row["cut"] = c - prev
        row["cut_ratio"] = round(row["cut"] / max(total, 1), 6)
        row["cut_cum"] = c
        prev = c
    return rows


def _spill_intra(stream, assign, k1, chunk_edges, tmpdir, local_id):
    """One streaming pass: write each part's intra edges — relabeled to
    the part's dense local ids — to ``tmpdir/p{p}.bin32``. Returns the
    per-part file paths. O(chunk) transient memory; each chunk is
    grouped by owning part once (stable argsort + boundary scan).

    File handles are bounded by an LRU of ``_SPILL_MAX_FDS`` append-mode
    handles (review finding: k1 simultaneous 1 MB-buffered handles at
    --k-levels 1024,2 would blow both ulimit -n and the documented
    O(V + chunk) envelope); each chunk writes one contiguous slice per
    part, so reopen churn is at most one open per touched part per
    chunk."""
    from collections import OrderedDict

    paths = [os.path.join(tmpdir, f"p{p}.bin32") for p in range(k1)]
    for p in paths:  # every part gets a (possibly empty) shard file
        open(p, "wb").close()
    lru: OrderedDict[int, object] = OrderedDict()

    def handle(p):
        f = lru.get(p)
        if f is not None:
            lru.move_to_end(p)
            return f
        if len(lru) >= _SPILL_MAX_FDS:
            _, old = lru.popitem(last=False)
            old.close()
        f = lru[p] = open(paths[p], "ab", buffering=1 << 16)
        return f

    try:
        for c in stream.chunks(chunk_edges):
            e = np.asarray(c, np.int64).reshape(-1, 2)
            pu = assign[e[:, 0]]
            keep = pu == assign[e[:, 1]]
            e = e[keep]
            pu = pu[keep]
            if not len(e):
                continue
            grp = np.argsort(pu, kind="stable")
            lo = local_id[e[grp]].astype(np.uint32)
            bounds = np.searchsorted(pu[grp], np.arange(k1 + 1))
            for p in range(k1):
                a, b = bounds[p], bounds[p + 1]
                if b > a:
                    handle(p).write(lo[a:b].tobytes())
    finally:
        for f in lru.values():
            f.close()
    return paths


def _save_hier(checkpointer, parts_done, assign, final, spill_names,
               spill_sizes, meta):
    """Level-boundary checkpoint (phase ``hier``, chunk_idx = per-part
    queue position): the level-0 result, the partial final assignment,
    and the spill-file manifest (shard basenames + byte sizes; -1 =
    shard consumed by a completed subtree). O(V) per save, like the
    flat phases."""
    checkpointer.save(
        "hier", int(parts_done),
        {"assign": np.asarray(assign, np.int32),
         "final": np.asarray(final, np.int32),
         "level": np.int64(0),
         "spill_names": np.asarray(list(spill_names)),
         "spill_sizes": np.asarray(spill_sizes, np.int64)}, meta)


def _spill_manifest_problem(level_dir, names, sizes, parts_done):
    """None when every still-pending shard named in the manifest exists
    with its recorded byte size; else a description — the caller
    degrades to a from-scratch level rebuild with a warning instead of
    resuming against missing/torn spill state."""
    for p, (name, size) in enumerate(zip(names, sizes)):
        if p < parts_done or int(size) < 0:
            continue
        shard = os.path.join(level_dir, str(name))
        try:
            got = os.path.getsize(shard)
        except OSError:
            return f"spill shard {name} missing"
        if got != int(size):
            return f"spill shard {name} is {got} bytes, manifest says " \
                   f"{int(size)}"
    return None


def _hier_assign(stream, k_levels, backend, refine, refine_alpha,
                 chunk_edges, tmpdir, opts, timings=None,
                 spill_bytes=None, depth=0, checkpointer=None,
                 resume=False, meta=None, nprocs=1):
    """Assignment over ``stream`` at k = prod(k_levels), recursing.
    ``timings`` (top-level dict) accumulates per-depth partition/spill
    walls under ``level{d}_partition`` / ``level{d}_spill`` keys;
    ``spill_bytes`` (its own dict — bytes are not seconds) accumulates
    per-depth spilled-shard sizes.

    ``checkpointer`` (depth 0 only — recursion passes None) arms the
    two recovery granularities documented in the module docstring;
    ``meta`` is the run fingerprint its saves carry. The level-0 flat
    partition runs under fault scope ``level0``, and each completed
    top-level part reports fault phase ``level``."""
    import time

    from sheep_tpu import _partition_stream, _resolve_backend
    from sheep_tpu.io.edgestream import EdgeStream
    from sheep_tpu.utils import checkpoint as ckpt_mod
    from sheep_tpu.utils import fault

    def t_add(key, dt):
        if timings is not None:
            timings[key] = round(timings.get(key, 0.0) + dt, 3)

    n = stream.num_vertices
    k1 = k_levels[0]
    k_sub = int(np.prod(k_levels[1:])) if len(k_levels) > 1 else 1

    state = ckpt_mod.resume_state(checkpointer, meta, resume,
                                  raise_on_mismatch=nprocs == 1)
    if nprocs > 1 and checkpointer is not None and resume:
        state = ckpt_mod.reconcile_multihost_resume(checkpointer, state,
                                                    meta)

    level_dir = None
    if checkpointer is not None:
        # deterministic shard home, reused across resumes; inner
        # recursion levels still use transient lvl_* dirs — stale ones
        # from a killed attempt are unreferenced, reclaim them
        level_dir = os.path.join(tmpdir, "level0_shards")
        for stale in glob.glob(os.path.join(tmpdir, "lvl_*")):
            shutil.rmtree(stale, ignore_errors=True)

    assign = final = None
    parts_done = 0
    spill_names: list = []
    spill_sizes = np.zeros(0, np.int64)
    if state is not None:
        assign = np.asarray(state.arrays["assign"], np.int32)
        final = np.asarray(state.arrays["final"], np.int32).copy()
        parts_done = int(state.chunk_idx)
        spill_names = [str(x) for x in state.arrays["spill_names"]]
        spill_sizes = np.asarray(state.arrays["spill_sizes"],
                                 np.int64).copy()
        problem = _spill_manifest_problem(level_dir, spill_names,
                                          spill_sizes, parts_done)
        if nprocs > 1:
            # degrading adds collective work (a level-0 rebuild), so
            # the verdict must be COLLECTIVE like reconcile's: one
            # process rebuilding alone would cross schedules with
            # peers that skipped straight to the recursion. (Reconcile
            # already agreed every process holds the same step, so all
            # processes reach this allgather together.)
            from jax.experimental import multihost_utils

            bad = np.asarray(multihost_utils.process_allgather(
                np.array([1 if problem is not None else 0], np.int64)))
            if bad.any() and problem is None:
                problem = "a peer process reported spill damage"
        if problem is not None:
            ckpt_mod._warn(
                f"hierarchy resume: {problem}; rebuilding the level "
                f"from scratch")
            state = None
        else:
            # the level-0 sub-domain is obsolete once a level-boundary
            # checkpoint exists; reclaim whatever a crash left there
            checkpointer.child("level0").clear(force=True)

    if state is None:
        level0_ck = None
        if checkpointer is not None and getattr(
                _resolve_backend(backend, {})[0], "supports_checkpoint",
                False):
            level0_ck = checkpointer.child("level0")
        t0 = time.perf_counter()
        # comm volume of inner levels is discarded (the final full-stream
        # score recomputes it once); chunk_edges forwards as the backends'
        # ctor option so the user's memory ceiling applies at every level
        with fault.scope("level0") if depth == 0 else nullcontext():
            with obs.span("hier_partition", level=depth, k=k1):
                res = _partition_stream(
                    stream, k1, backend=backend, refine=refine,
                    refine_alpha=refine_alpha, chunk_edges=chunk_edges,
                    **{**opts, "comm_volume": False},
                    **({"checkpointer": level0_ck, "resume": resume}
                       if level0_ck is not None else {}))
        assign = np.asarray(res.assignment, np.int32)
        t_add(f"level{depth}_partition", time.perf_counter() - t0)
    if len(k_levels) == 1:
        return assign

    # dense local ids for every part in one O(V) pass: vertex v is the
    # local_id[v]-th member of part assign[v]
    order = np.argsort(assign, kind="stable")
    counts = np.bincount(assign, minlength=k1).astype(np.int64)
    offsets = np.zeros(k1 + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])

    if state is None:
        local_id = np.empty(n, np.int32)
        local_id[order] = (np.arange(n, dtype=np.int64)
                           - np.repeat(offsets[:-1], counts)).astype(np.int32)
        if level_dir is None:
            level_dir = tempfile.mkdtemp(prefix="lvl_", dir=tmpdir)
        else:
            os.makedirs(level_dir, exist_ok=True)
        t0 = time.perf_counter()
        sp = obs.begin("hier_spill", level=depth, parts=k1)
        try:
            paths = _spill_intra(stream, assign, k1, chunk_edges,
                                 level_dir, local_id)
        finally:
            sp.end()
        t_add(f"level{depth}_spill", time.perf_counter() - t0)
        if spill_bytes is not None:
            key = f"level{depth}_spill_bytes"
            spill_bytes[key] = spill_bytes.get(key, 0) + sum(
                os.path.getsize(p) for p in paths)
        del local_id
        final = np.zeros(n, np.int32)
        parts_done = 0
        if checkpointer is not None:
            spill_names = [os.path.basename(p) for p in paths]
            spill_sizes = np.array([os.path.getsize(p) for p in paths],
                                   np.int64)
            # bank the level-0 result + shard manifest BEFORE dropping
            # the level-0 chunk checkpoints: at no instant is the only
            # copy of level-0 progress in volatile memory
            _save_hier(checkpointer, 0, assign, final, spill_names,
                       spill_sizes, meta)
            if level0_ck is not None:
                level0_ck.clear(force=True)
    else:
        paths = [os.path.join(level_dir, nm) for nm in spill_names]

    start_parts = parts_done
    pending_rm: list = []
    prev_rm: list = []

    def save_boundary(p_next):
        # consumed shards leave the manifest before their files leave
        # the disk — and the files outlive the manifest by ONE save:
        # load() may fall back to the RETAINED PREVIOUS step (corrupt
        # latest .npz, multi-host one-step skew), whose manifest still
        # names the shards this save marks consumed. Only shards
        # already absent from BOTH retained manifests are removed.
        for q in pending_rm:
            spill_sizes[q] = -1
        _save_hier(checkpointer, p_next, assign, final, spill_names,
                   spill_sizes, meta)
        for q in prev_rm:
            try:
                os.remove(paths[q])
            except OSError:
                pass
        prev_rm[:] = pending_rm
        pending_rm.clear()

    ok = False
    try:
        for p in range(parts_done, k1):
            members = order[offsets[p]:offsets[p + 1]]
            if len(members) == 0:
                pass
            elif len(members) <= k_sub:
                # degenerate tiny part: round-robin keeps every label in
                # [0, k_sub); final_refine repairs these choices where a
                # better neighborhood exists
                final[members] = p * k_sub + np.arange(
                    len(members), dtype=np.int32) % k_sub
            else:
                sub = EdgeStream.open(paths[p], n_vertices=len(members))
                sub_assign = _hier_assign(sub, k_levels[1:], backend,
                                          refine, refine_alpha,
                                          chunk_edges, tmpdir, opts,
                                          timings=timings,
                                          spill_bytes=spill_bytes,
                                          depth=depth + 1)
                final[members] = p * k_sub + sub_assign
                if checkpointer is None:
                    os.remove(paths[p])  # subtree done: reclaim early
                else:
                    pending_rm.append(p)
            if checkpointer is not None and (
                    p == k1 - 1 or checkpointer.due_span(p, p + 1)):
                save_boundary(p + 1)
            if depth == 0:
                fault.maybe_fail("level", p + 1 - start_parts)
        ok = True
    finally:
        # with a checkpointer, a fault must leave the shards for resume
        if level_dir is not None and (ok or checkpointer is None):
            shutil.rmtree(level_dir, ignore_errors=True)
    return final


def partition_hierarchical(path, k_levels, backend=None, refine=8,
                           refine_alpha: float = 1.10,
                           chunk_edges: int = 1 << 22,
                           balance: float | None = None,
                           final_refine: int = 0,
                           spill_dir: str | None = None,
                           n_vertices: int | None = None,
                           refine_budget_bytes: int = 4 << 30,
                           checkpointer=None, resume: bool = False,
                           nprocs: int = 1, **opts):
    """Partition into prod(k_levels) parts, one level at a time.

    ``k_levels`` — e.g. ``[8, 8]`` for k=64. ``refine`` rounds apply at
    EVERY level (that is the point: each level stays above the LP
    signal threshold). ``balance=BETA`` budgets the end-to-end balance
    bound as BETA**(1/levels) per level (mutually exclusive with an
    explicit ``alpha``). ``final_refine=N`` adds N warm-start LP rounds
    at the FULL k after assembly — the level-1 leakage repair. Extra
    ``opts`` are the usual backend/partition options of
    :func:`sheep_tpu.partition`. Returns a PartitionResult scored over
    the full stream at k = prod(k_levels); ``backend`` in the result is
    tagged ``+hier``.

    ``checkpointer``/``resume`` (utils/checkpoint.Checkpointer) make
    the run recoverable at chunk granularity inside level 0 and at
    level boundaries for the recursion (module docstring); the spill
    shards live under the checkpoint dir so a resumed run reuses them.
    A successful run clears its checkpoint state like the flat
    backends. ``nprocs`` > 1 reconciles the level-boundary resume step
    across processes the way the flat multi-host paths do (level 0 is
    an ordinary flat partition, so multi-host applies there; every
    process then replays the identical deterministic recursion in
    lockstep over its own spill copy).
    """
    from sheep_tpu.backends.base import score_stream
    from sheep_tpu.io.edgestream import open_input

    from sheep_tpu import _resolve_backend, comm_volume_of, refine_result

    k_levels = [int(k) for k in k_levels]
    if len(k_levels) < 1 or any(k < 1 for k in k_levels):
        raise ValueError(f"k_levels must be positive ints, got {k_levels}")
    k_total = int(np.prod(k_levels))
    if balance is not None:
        if balance <= 1.0:
            raise ValueError(f"balance must be > 1, got {balance}")
        if "alpha" in opts and opts["alpha"] != 1.0:
            raise ValueError("balance sets the per-level alpha; do not "
                             "also pass alpha")
        beta_level = balance ** (1.0 / len(k_levels))
        opts = {**opts, "alpha": min(beta_level - 1.0, 1.0)}
        # per-level refine must not void the budget it refines under
        refine_alpha = min(refine_alpha, beta_level)
    comm_volume = opts.get("comm_volume", True)
    inner_backend = _resolve_backend(backend, {})[0].name

    import time

    if checkpointer is not None:
        # spill shards must survive the process to be resumable: root
        # them under the checkpoint dir, per process (each multi-host
        # process streams its own spill copy)
        tmp_root = os.path.join(checkpointer.dir,
                                f"hier_spill_p{checkpointer.process}")
        os.makedirs(tmp_root, exist_ok=True)
    else:
        tmp_root = tempfile.mkdtemp(prefix="sheep_hier_", dir=spill_dir)
    timings: dict = {}
    spill_bytes: dict = {}
    try:
        # headerless binary formats otherwise pay a full stream scan
        # just to learn V (30 GB at the uk-class soak)
        with open_input(path, n_vertices=n_vertices) as es:
            meta = None
            if checkpointer is not None:
                from sheep_tpu.utils import checkpoint as ckpt_mod

                # every option that affects the result fingerprints the
                # run, exactly like the flat backends' stream_meta use
                meta = ckpt_mod.stream_meta(
                    es, k_total, chunk_edges,
                    weights=opts.get("weights", "unit"),
                    alpha=opts.get("alpha", 1.0),
                    comm_volume=comm_volume, state_format="hier",
                    k_levels=[int(k) for k in k_levels],
                    refine=int(refine), refine_alpha=float(refine_alpha),
                    final_refine=int(final_refine),
                    inner_backend=inner_backend)
            final = _hier_assign(es, k_levels, backend, refine,
                                 refine_alpha, chunk_edges, tmp_root,
                                 dict(opts), timings=timings,
                                 spill_bytes=spill_bytes,
                                 checkpointer=checkpointer,
                                 resume=resume, meta=meta,
                                 nprocs=nprocs)
            w = None
            if opts.get("weights") == "degree":
                # score with the same weights the levels balanced
                # against, like partition()/partition_multi
                t0 = time.perf_counter()
                n = es.num_vertices
                w = np.zeros(n, dtype=np.int64)
                for c in es.chunks(chunk_edges):
                    w += np.bincount(np.asarray(c, np.int64).ravel(),
                                     minlength=n)[:n]
                timings["degrees_weights"] = round(
                    time.perf_counter() - t0, 3)
            # with a final refine coming, the pre-refine comm volume
            # would be recomputed and discarded — defer it to one pass
            # over the FINAL assignment (review finding)
            t0 = time.perf_counter()
            scored = score_stream(es, {k_total: final},
                                  chunk_edges=chunk_edges,
                                  comm_volume=comm_volume
                                  and not final_refine, weights=w)
            timings["score"] = round(time.perf_counter() - t0, 3)
            cut, total, balance_got, cv = scored[k_total]
            from sheep_tpu.types import PartitionResult

            res = PartitionResult(
                assignment=final, k=k_total, edge_cut=cut,
                total_edges=total, cut_ratio=cut / max(total, 1),
                balance=balance_got, comm_volume=cv,
                phase_times=timings,
                backend=f"{inner_backend}+hier{k_levels}",
                diagnostics=spill_bytes)
            if final_refine:
                # warm-start boundary repair at the full k; the cap is
                # the end-to-end budget when one was given. The degree
                # table computed for scoring is reused, not re-streamed.
                t0 = time.perf_counter()
                res = refine_result(
                    res, es, rounds=final_refine,
                    alpha=balance if balance is not None else refine_alpha,
                    weights=opts.get("weights", "unit"), degrees=w,
                    budget_bytes=refine_budget_bytes)
                res.phase_times["final_refine"] = round(
                    time.perf_counter() - t0, 3)
                if comm_volume:
                    import dataclasses

                    t0 = time.perf_counter()
                    res = dataclasses.replace(
                        res, comm_volume=comm_volume_of(
                            res.assignment, es, es.num_vertices, k_total,
                            chunk_edges))
                    res.phase_times["comm_volume"] = round(
                        time.perf_counter() - t0, 3)
            # ---- cut ledger (ISSUE 13) -------------------------------
            # Per-level attribution of the FINAL cut (post-refine when a
            # final refine ran: the ledger must price what shipped, not
            # an intermediate), plus capacity-freeze accounting at the
            # full k — one extra cut-only stream pass, the price of
            # turning one opaque number into a per-level diagnosis.
            t0 = time.perf_counter()
            ledger = level_ledger(es, res.assignment, k_levels,
                                  res.edge_cut, res.total_edges,
                                  chunk_edges=chunk_edges)
            from sheep_tpu.ops.score import part_loads_accounting

            alpha_rep = balance if balance is not None else refine_alpha
            cap = (alpha_rep * (-(-len(res.assignment) // k_total))
                   if w is None else
                   alpha_rep * float(np.sum(w)) / k_total)
            acct = part_loads_accounting(res.assignment, k_total,
                                         weights=w, cap=cap)
            for row in ledger:
                res.diagnostics[f"cut_level{row['level']}"] = row["cut"]
                res.diagnostics[f"cut_ratio_level{row['level']}"] = \
                    row["cut_ratio"]
            res.diagnostics["ledger_parts_at_capacity"] = \
                acct["parts_at_capacity"]
            res.diagnostics["ledger_frozen_load_fraction"] = \
                acct["frozen_load_fraction"]
            repaired = None
            if final_refine:
                before = res.diagnostics.get("refine_cut_before")
                after = res.diagnostics.get("refine_cut_after")
                if before is not None and after is not None:
                    repaired = int(before - after)
                    res.diagnostics["final_refine_repaired"] = repaired
            timings["ledger"] = round(time.perf_counter() - t0, 3)
            obs.event(
                "quality_ledger", k=k_total,
                k_levels=[int(x) for x in k_levels],
                edge_cut=int(res.edge_cut),
                total_edges=int(res.total_edges),
                cut_ratio=round(float(res.cut_ratio), 6),
                balance=round(float(res.balance), 4),
                levels=[{kk: int(v) if kk != "cut_ratio" else v
                         for kk, v in row.items()} for row in ledger],
                final_refine_repaired=repaired,
                parts_at_capacity=acct["parts_at_capacity"],
                frozen_load_fraction=acct["frozen_load_fraction"])
            if checkpointer is not None:
                # success: drop the boundary state, the nested level-0
                # domain, and the persistent spill root (the flat
                # backends' clear-on-success contract)
                checkpointer.clear(force=True)
                shutil.rmtree(os.path.join(checkpointer.dir, "level0"),
                              ignore_errors=True)
                shutil.rmtree(tmp_root, ignore_errors=True)
            return res
    finally:
        if checkpointer is None:
            # a faulted checkpointed run must keep its spill shards for
            # the resume; un-checkpointed runs clean up unconditionally
            shutil.rmtree(tmp_root, ignore_errors=True)
