"""Edge assignment + scoring on device (SURVEY.md §2 #8, §3.4).

One gathered pass per chunk: part lookups for both endpoints, predicated
counter reductions. All device arithmetic is int32 (int64 is emulated on
TPU); per-chunk counters are exact because chunks are < 2^31 edges, and
cross-chunk accumulation happens in host Python ints / numpy int64.
Multi-device reductions are a ``psum`` in the sharded pipeline.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def score_chunk(edges: jax.Array, assign: jax.Array, n: int):
    """(cut, total) int32 counts for one (C, 2) chunk.

    assign is int32[n+1] (sentinel slot ignored). Padding = endpoints
    outside [0, n)."""
    e = edges.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]
    cut = jnp.sum(valid & (pu != pv), dtype=jnp.int32)
    total = jnp.sum(valid, dtype=jnp.int32)
    return cut, total


@partial(jax.jit, static_argnames=("n",))
def cut_pairs(edges: jax.Array, assign: jax.Array, n: int):
    """(2C, 2) int32 [vertex, foreign_part] rows for cut edges; non-cut and
    padding rows are the sentinel (n, 0). Comm volume = number of distinct
    non-sentinel rows across all chunks (uniqued host-side in int64)."""
    e = edges.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]
    is_cut = valid & (pu != pv)
    sent_v = jnp.int32(n)
    row_u = jnp.stack([jnp.where(is_cut, u, sent_v), jnp.where(is_cut, pv, 0)], axis=1)
    row_v = jnp.stack([jnp.where(is_cut, v, sent_v), jnp.where(is_cut, pu, 0)], axis=1)
    return jnp.concatenate([row_u, row_v])


# pending cv keys are compacted (sort+unique) whenever the accumulator
# exceeds this many entries, bounding host memory at O(distinct + cap)
# instead of O(all cut-edge endpoints seen) (VERDICT r1 weak #5)
CV_COMPACT_ENTRIES = 1 << 25  # 256 MiB of int64 keys


def accumulate_cv_keys(cv_chunks: list, keys) -> list:
    """Append a chunk's cv keys; compact in place when the PENDING tail
    (everything after the already-compacted head) exceeds the cap.
    Head-excluded accounting keeps memory at O(distinct + cap) without
    going quadratic when the distinct-key set alone exceeds the cap
    (re-sorting the whole accumulator per chunk)."""
    cv_chunks.append(keys)
    if (len(cv_chunks) > 1
            and sum(len(c) for c in cv_chunks[1:]) > CV_COMPACT_ENTRIES):
        from sheep_tpu.utils.checkpoint import compact_cv_keys

        compacted = compact_cv_keys(cv_chunks)
        cv_chunks.clear()
        cv_chunks.append(compacted)
    return cv_chunks


@partial(jax.jit, static_argnames=("n", "cap"))
def cut_pair_rows_compact(edges: jax.Array, assign: jax.Array, n: int,
                          cap: int):
    """Device-side sorted-unique cut rows, compacted to (cap, 2).

    Returns (rows, distinct_count): rows are the chunk's DISTINCT
    (vertex, foreign_part) pairs padded with the sentinel (n, 0); the
    compaction is valid only when distinct_count <= cap — past that the
    caller falls back to the dense pull. Power-law chunks repeat the same
    hub/part pairs constantly, so the device dedup shrinks the
    host transfer from 2C rows to min(distinct, cap) rows."""
    rows = cut_pairs(edges, assign, n)
    v, p = rows[:, 0], rows[:, 1]
    idx = jnp.lexsort((p, v))
    v2, p2 = v[idx], p[idx]
    first = jnp.concatenate([
        jnp.ones(1, bool), (v2[1:] != v2[:-1]) | (p2[1:] != p2[:-1])])
    keep = first & (v2 < n)
    count = jnp.sum(keep, dtype=jnp.int32)
    # fill slots index an appended sentinel row (same trick as
    # elim.compact_actives), so padding is inert
    sel = jnp.nonzero(keep, size=cap, fill_value=v2.shape[0])[0]
    v3 = jnp.concatenate([v2, jnp.full(1, n, v2.dtype)])[sel]
    p3 = jnp.concatenate([p2, jnp.zeros(1, p2.dtype)])[sel]
    return jnp.stack([v3, p3], axis=1), count


def _compact_cap(c_rows: int) -> int:
    """Device-compaction capacity for a chunk producing c_rows rows."""
    from sheep_tpu.ops.elim import pow2_at_least

    return min(c_rows, pow2_at_least(c_rows >> 3, floor=1 << 16))


def part_loads_accounting(assign, k: int, weights=None,
                          cap: float = None) -> dict:
    """Balance/capacity-freeze accounting of one assignment (ISSUE 13
    cut ledger): per-part load spread plus — when ``cap`` is given (the
    split's ``alpha * total/k`` bag capacity or refine's
    ``alpha * ceil(n/k)`` move cap) — how many parts sit AT/ABOVE it.
    A part at capacity is FROZEN for every capacity-respecting repair
    pass (refine can only shrink it), so cut stuck behind frozen parts
    is attributable to the balance budget, not to the LP signal. Host
    numpy, O(V): callers gate on need (the ledger, a traced split)."""
    import numpy as np

    a = np.asarray(assign)
    if weights is None:
        loads = np.bincount(a, minlength=k).astype(np.float64)
    else:
        loads = np.bincount(a, weights=np.asarray(weights, np.float64),
                            minlength=k)
    total = float(loads.sum())
    mean = total / max(k, 1)
    out = {"balance": float(loads.max() / mean) if mean > 0 else 1.0,
           "max_load": float(loads.max()), "min_load": float(loads.min()),
           "empty_parts": int((loads == 0).sum())}
    if cap is not None:
        at_cap = loads >= float(cap)
        out["cap"] = float(cap)
        out["parts_at_capacity"] = int(at_cap.sum())
        out["frozen_load_fraction"] = round(
            float(loads[at_cap].sum() / total) if total else 0.0, 6)
    return out


def edge_effect_host(edges, assignments: dict, n: int) -> tuple:
    """Host twin of :func:`score_chunk` for O(Δ) delta accounting
    (ISSUE 17 incremental scoring): ``(valid_count, {k: cut_count})``
    of one delta batch under EXISTING assignments. Same validity mask
    as the streamed scorers — endpoints in [0, n), no self-loops — so
    an incrementally maintained (cut, total) stays bit-equal to a full
    ``score_stream`` pass over the mutated survivor multiset."""
    import numpy as np

    e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    cuts = {}
    for k, a in assignments.items():
        uc, vc = u[valid], v[valid]
        cuts[k] = int(np.count_nonzero(a[uc] != a[vc]))
    return int(np.count_nonzero(valid)), cuts


def cut_pair_keys_host(chunk, assign, n: int, k: int):
    """Run cut_pairs on a (C, 2) or (D, C, 2) chunk and return the encoded
    int64 keys (vertex * k + foreign_part) on host — the shared comm-volume
    accumulation used by every backend. Pulls the device-deduped compact
    rows when they fit the capacity, the dense row dump otherwise."""
    import numpy as np

    arr = np.asarray(chunk)
    rows_all = []
    for c in arr.reshape(-1, arr.shape[-2], 2) if arr.ndim == 3 else [arr]:
        cap = _compact_cap(2 * c.shape[0])
        if cap < 2 * c.shape[0]:
            compact, count = cut_pair_rows_compact(c, assign, n, cap)
            # designed pulls: this helper IS the host accumulation step
            if int(count) <= cap:  # sheeplint: sync-ok
                rows = np.asarray(compact)  # sheeplint: sync-ok
                rows = rows[rows[:, 0] < n]
                rows_all.append(rows[:, 0].astype(np.int64) * k + rows[:, 1])
                continue
        rows = np.asarray(cut_pairs(c, assign, n))  # sheeplint: sync-ok
        rows = rows[rows[:, 0] < n]
        rows_all.append(rows[:, 0].astype(np.int64) * k + rows[:, 1])
    return np.concatenate(rows_all) if rows_all else np.zeros(0, np.int64)


# -- distributed incremental rescore (ISSUE 19) ------------------------
# One compiled rescore program per (mesh, arc capacity, K): cached here
# so repeat epochs at similar delta sizes never recompile (the sheeplint
# ``fold`` rule's contract for the update path).
_MOVE_RESCORE_CACHE: dict = {}


def _make_move_rescore(mesh):
    """Build the jitted all-k rescore program for ``mesh``: arcs shard
    over the devices, the per-k assignment/mask tables replicate, and
    the per-shard (not-both, both) partial sums ride out through ONE
    psum — the single all-reduce a scored resident epoch pays."""
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sheep_tpu.parallel.mesh import SHARD_AXIS, shard_map

    shard = NamedSharding(mesh, P(SHARD_AXIS))
    repl = NamedSharding(mesh, P())

    @partial(jax.jit,
             in_shardings=(shard, shard, repl, repl, repl),
             out_shardings=repl)
    def rescore(su, du, prev_t, new_t, mask_t):
        def f(s_l, d_l, prev_, new_, mask_):
            keep = mask_[:, s_l]                       # (K, a)
            both = mask_[:, d_l]
            diff = (new_[:, s_l] != new_[:, d_l]).astype(jnp.int32) \
                - (prev_[:, s_l] != prev_[:, d_l]).astype(jnp.int32)
            dk = jnp.where(keep, diff, 0)
            s_nb = jnp.sum(jnp.where(both, 0, dk), axis=1,
                           dtype=jnp.int32)
            s_b = jnp.sum(jnp.where(both, dk, 0), axis=1,
                          dtype=jnp.int32)
            return lax.psum(jnp.stack([s_nb, s_b], axis=1), SHARD_AXIS)
        return shard_map(f, mesh=mesh,
                         in_specs=(P(SHARD_AXIS), P(SHARD_AXIS),
                                   P(), P(), P()),
                         out_specs=P())(su, du, prev_t, new_t, mask_t)

    return rescore


def move_rescore_sharded(src, dst, prevs: dict, news: dict,
                         masks: dict, mesh) -> dict:
    """Distributed twin of :func:`sheep_tpu.ops.refine.move_rescore_host`
    (ISSUE 19 tentpole b): exact per-k edge-cut deltas of a batch of
    part moves, computed from per-shard partial sums all-reduced ONCE
    for every k together.

    Bit-equal to the host scorer by construction: integer addition is
    associative, so sharding the kept arcs and psumming the (not-both,
    both) partials reproduces the host sums exactly; the both-changed
    halving divides only AFTER the global reduction (a per-shard "both"
    partial may be odd — only the global one is guaranteed even by arc
    symmetry, asserted here like the host path). Per-shard counts stay
    int32-exact because each shard sees < 2^31 arcs (the same bound
    :func:`score_chunk` leans on). Sentinel-padded arc slots index the
    tables' sentinel row (mask false) and contribute nothing.

    ``prevs`` / ``news`` / ``masks`` are ``{k: array[V]}`` for the ks
    whose assignment actually moved; returns ``{k: cut_delta}``."""
    import numpy as np

    from sheep_tpu.ops.elim import pow2_at_least

    ks = list(prevs)
    out = {k: 0 for k in ks}
    s = np.asarray(src)
    d = np.asarray(dst)
    if not len(s) or not ks:
        return out
    n = int(len(next(iter(prevs.values()))))
    dev = int(mesh.devices.size)
    cap = pow2_at_least(-(-len(s) // dev), floor=1 << 10) * dev
    su = np.full(cap, n, np.int32)
    du = np.full(cap, n, np.int32)
    su[:len(s)] = s
    du[:len(d)] = d
    kk = len(ks)
    prev_t = np.zeros((kk, n + 1), np.int32)
    new_t = np.zeros((kk, n + 1), np.int32)
    mask_t = np.zeros((kk, n + 1), bool)
    for i, k in enumerate(ks):
        prev_t[i, :n] = prevs[k]
        new_t[i, :n] = news[k]
        mask_t[i, :n] = masks[k]
    fn = _MOVE_RESCORE_CACHE.get(mesh)
    if fn is None:
        fn = _MOVE_RESCORE_CACHE[mesh] = _make_move_rescore(mesh)
    part = np.asarray(  # sheeplint: sync-ok (the one designed pull)
        fn(su, du, prev_t, new_t, mask_t))
    for i, k in enumerate(ks):
        s_nb, s_b = int(part[i, 0]), int(part[i, 1])
        # symmetric arcs: the global both-changed sum is even (the
        # per-shard partials need not be — divide after the psum)
        assert s_b % 2 == 0
        out[k] = s_nb + s_b // 2
    return out
