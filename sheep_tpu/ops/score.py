"""Edge assignment + scoring on device (SURVEY.md §2 #8, §3.4).

One gathered pass per chunk: part lookups for both endpoints, predicated
counter reductions. All device arithmetic is int32 (int64 is emulated on
TPU); per-chunk counters are exact because chunks are < 2^31 edges, and
cross-chunk accumulation happens in host Python ints / numpy int64.
Multi-device reductions are a ``psum`` in the sharded pipeline.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def score_chunk(edges: jax.Array, assign: jax.Array, n: int):
    """(cut, total) int32 counts for one (C, 2) chunk.

    assign is int32[n+1] (sentinel slot ignored). Padding = endpoints
    outside [0, n)."""
    e = edges.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]
    cut = jnp.sum(valid & (pu != pv), dtype=jnp.int32)
    total = jnp.sum(valid, dtype=jnp.int32)
    return cut, total


@partial(jax.jit, static_argnames=("n",))
def cut_pairs(edges: jax.Array, assign: jax.Array, n: int):
    """(2C, 2) int32 [vertex, foreign_part] rows for cut edges; non-cut and
    padding rows are the sentinel (n, 0). Comm volume = number of distinct
    non-sentinel rows across all chunks (uniqued host-side in int64)."""
    e = edges.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]
    is_cut = valid & (pu != pv)
    sent_v = jnp.int32(n)
    row_u = jnp.stack([jnp.where(is_cut, u, sent_v), jnp.where(is_cut, pv, 0)], axis=1)
    row_v = jnp.stack([jnp.where(is_cut, v, sent_v), jnp.where(is_cut, pu, 0)], axis=1)
    return jnp.concatenate([row_u, row_v])


# pending cv keys are compacted (sort+unique) whenever the accumulator
# exceeds this many entries, bounding host memory at O(distinct + cap)
# instead of O(all cut-edge endpoints seen) (VERDICT r1 weak #5)
CV_COMPACT_ENTRIES = 1 << 25  # 256 MiB of int64 keys


def accumulate_cv_keys(cv_chunks: list, keys) -> list:
    """Append a chunk's cv keys; compact in place past the size cap."""
    cv_chunks.append(keys)
    if (len(cv_chunks) > 1
            and sum(len(c) for c in cv_chunks) > CV_COMPACT_ENTRIES):
        from sheep_tpu.utils.checkpoint import compact_cv_keys

        compacted = compact_cv_keys(cv_chunks)
        cv_chunks.clear()
        cv_chunks.append(compacted)
    return cv_chunks


def cut_pair_keys_host(chunk, assign, n: int, k: int):
    """Run cut_pairs on a (C, 2) or (D, C, 2) chunk and return the encoded
    int64 keys (vertex * k + foreign_part) on host — the shared comm-volume
    accumulation used by every backend."""
    import numpy as np

    arr = np.asarray(chunk)
    rows_all = []
    for c in arr.reshape(-1, arr.shape[-2], 2) if arr.ndim == 3 else [arr]:
        rows = np.asarray(cut_pairs(c, assign, n))
        rows = rows[rows[:, 0] < n]
        rows_all.append(rows[:, 0].astype(np.int64) * k + rows[:, 1])
    return np.concatenate(rows_all) if rows_all else np.zeros(0, np.int64)
