"""Degree accumulation on device (SURVEY.md §2 #3).

Endpoint-count degrees via scatter-add — XLA lowers ``.at[].add`` to an
efficient sorted segment update on TPU. Padding convention: edges padded
with endpoint == n land in an extra slot that is dropped by the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("n",))
def degree_chunk(deg: jax.Array, edges: jax.Array, n: int) -> jax.Array:
    """Accumulate endpoint counts of one (C, 2) chunk into deg (int32[n+1]).

    Slot n absorbs padding; self-loops count twice (matches the CPU core).
    """
    idx = jnp.clip(edges.reshape(-1), 0, n)
    return deg.at[idx].add(1, mode="drop")


def init_degrees(n: int) -> jax.Array:
    return jnp.zeros(n + 1, dtype=jnp.int32)
