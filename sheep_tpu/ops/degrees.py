"""Degree accumulation on device (SURVEY.md §2 #3).

Endpoint-count degrees via scatter-add — XLA lowers ``.at[].add`` to an
efficient sorted segment update on TPU. Padding convention: edges padded
with endpoint == n land in an extra slot that is dropped by the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("n",))
def degree_chunk(deg: jax.Array, edges: jax.Array, n: int) -> jax.Array:
    """Accumulate endpoint counts of one (C, 2) chunk into deg (int32[n+1]).

    Slot n absorbs padding; self-loops count twice (matches the CPU core).
    """
    idx = jnp.clip(edges.reshape(-1), 0, n)
    return deg.at[idx].add(1, mode="drop")


def init_degrees(n: int) -> jax.Array:
    return jnp.zeros(n + 1, dtype=jnp.int32)


def flush_every_for(chunk_edges: int) -> int:
    """Chunks between flushes of the int32 device accumulator into the
    int64 host totals: flush BEFORE any vertex could possibly see 2^31
    endpoints, so trillion-edge streams cannot overflow. Shared by the
    tpu backend and the server engine — the served build's degree
    totals must accumulate exactly like the CLI's for the bit-identity
    contract."""
    return max(1, (2**31 - 1) // max(2 * chunk_edges, 1))


def rank_clip_i32(deg_host):
    """int64 host degree totals -> int32-safe sort keys for the device
    elimination order. Degree values only matter ORDINALLY, so totals
    past int32 range are replaced by their stable ranks (double
    argsort); below it the totals pass through unchanged. Shared by
    the tpu backend and the server engine (same bit-identity argument
    as :func:`flush_every_for`)."""
    import numpy as np

    if deg_host.size == 0 or deg_host.max() < 2**31:
        return deg_host
    return np.argsort(np.argsort(deg_host, kind="stable"),
                      kind="stable")
