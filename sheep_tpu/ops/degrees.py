"""Degree accumulation on device (SURVEY.md §2 #3).

Endpoint-count degrees via scatter-add — XLA lowers ``.at[].add`` to an
efficient sorted segment update on TPU. Padding convention: edges padded
with endpoint == n land in an extra slot that is dropped by the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("n",))
def degree_chunk(deg: jax.Array, edges: jax.Array, n: int) -> jax.Array:
    """Accumulate endpoint counts of one (C, 2) chunk into deg (int32[n+1]).

    Slot n absorbs padding; self-loops count twice (matches the CPU core).
    """
    idx = jnp.clip(edges.reshape(-1), 0, n)
    return deg.at[idx].add(1, mode="drop")


def init_degrees(n: int) -> jax.Array:
    return jnp.zeros(n + 1, dtype=jnp.int32)


def flush_every_for(chunk_edges: int) -> int:
    """Chunks between flushes of the int32 device accumulator into the
    int64 host totals: flush BEFORE any vertex could possibly see 2^31
    endpoints, so trillion-edge streams cannot overflow. Shared by the
    tpu backend and the server engine — the served build's degree
    totals must accumulate exactly like the CLI's for the bit-identity
    contract."""
    return max(1, (2**31 - 1) // max(2 * chunk_edges, 1))


# The measured LP signal law (BASELINE.md "SBM quality", hierarchy.py):
# label-propagation refinement recovers community structure only while
# average intra-community degree / k >= ~1 — below it the per-part
# majority is tie-noise and flat refine stalls (0.847 at s22 k=64 vs
# the 0.1252 hierarchical recipe). The advisor prices exactly this
# signal from the degree pass's cheapest statistic (2E/V; for a
# community graph the intra degree is within a small factor of it) and
# picks the hierarchy recipe that keeps EVERY level above threshold —
# the 2PS move: a degree-distribution signal chooses the strategy up
# front instead of after a wasted build.
LP_SIGNAL_THRESHOLD = 1.0

# the measured winning recipe's repair knobs (ROADMAP item 4 / BASELINE
# "SBM quality"): warm-start boundary repair at the full k, and a tight
# balance budget so the repair has headroom without voiding balance
ADVISED_FINAL_REFINE = 10
ADVISED_BALANCE = 1.05


def intra_signal(n: int, m: int, k: int) -> float:
    """The advisor's signal: average degree (2E/V) per part at ``k``."""
    return (2.0 * m / max(n, 1)) / max(k, 1)


def _prime_factors(k: int) -> list:
    out = []
    d = 2
    while d * d <= k:
        while k % d == 0:
            out.append(d)
            k //= d
        d += 1
    if k > 1:
        out.append(k)
    return out


def _equal_factors(k: int, nlevels: int):
    """Split k into ``nlevels`` near-equal integer factors (largest
    first), or None when k has fewer prime factors than levels."""
    primes = _prime_factors(k)
    if len(primes) < nlevels:
        return None
    buckets = [1] * nlevels
    for p in sorted(primes, reverse=True):
        buckets[buckets.index(min(buckets))] *= p
    return sorted(buckets, reverse=True)


def factor_levels(k: int, cap: int):
    """The fewest near-equal levels with every factor <= cap (each
    level's k stays above the signal threshold), or None when no such
    split exists (k prime and above cap). k=64 at cap=32 -> [8, 8] —
    the measured winning split."""
    import math

    if k <= cap:
        return [k]
    if cap < 2:
        cap = 2
    nlevels = max(2, math.ceil(math.log(k) / math.log(cap)))
    while nlevels <= k.bit_length() + 1:
        fac = _equal_factors(k, nlevels)
        if fac is None:
            return None  # fewer prime factors than levels: no split
        if fac[0] <= cap:
            return fac
        nlevels += 1
    return None


def advise_recipe(n: int, m, k: int,
                  threshold: float = LP_SIGNAL_THRESHOLD) -> dict:
    """The quality advisor's verdict for a flat build at ``k``
    (ISSUE 13): ``mode`` is ``"flat"`` (signal healthy — run as asked),
    ``"hier"`` (flat LP will stall; ``k_levels``/``final_refine``/
    ``balance`` carry the recommended recipe), or ``"unknown"`` (the
    edge count is not O(1)-knowable, so the signal isn't either).
    ``m`` may be None (unknown)."""
    if m is None:
        return {"mode": "unknown", "signal": None, "k": int(k)}
    sig = intra_signal(n, m, k)
    out = {"mode": "flat", "signal": round(sig, 4),
           "threshold": threshold, "k": int(k)}
    if k < 4 or sig >= threshold:
        return out
    avg_deg = 2.0 * m / max(n, 1)
    levels = factor_levels(int(k), max(2, int(avg_deg / threshold)))
    if levels is None or len(levels) < 2:
        return out  # no usable split (prime k past the cap): stay flat
    out.update(mode="hier", k_levels=levels,
               final_refine=ADVISED_FINAL_REFINE,
               balance=ADVISED_BALANCE)
    return out


def rank_clip_i32(deg_host):
    """int64 host degree totals -> int32-safe sort keys for the device
    elimination order. Degree values only matter ORDINALLY, so totals
    past int32 range are replaced by their stable ranks (double
    argsort); below it the totals pass through unchanged. Shared by
    the tpu backend and the server engine (same bit-identity argument
    as :func:`flush_every_for`)."""
    import numpy as np

    if deg_host.size == 0 or deg_host.max() < 2**31:
        return deg_host
    return np.argsort(np.argsort(deg_host, kind="stable"),
                      kind="stable")
