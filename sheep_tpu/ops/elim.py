"""Elimination-tree build as a data-parallel fixpoint (SURVEY.md §2 #4-6).

This is the TPU answer to the reference's sequential union-find hot loop
(SURVEY.md §7 hard part #1). Instead of pointer-chasing per edge, the
build is a *constraint-rewriting fixpoint*: the carried forest lives in a
persistent ``minp`` table (minp[x] = elimination position of x's parent,
n = none) and only the chunk's C edges are ever active:

    invariant  pos[lo] < pos[hi] for every active edge (lo, hi)
    round:
      minp[x] <- min(minp[x], pos of hi over active edges at lo=x)
                                                          (scatter-min)
      an active edge (x, v) with pos[v] == minp[x] RETIRES — it is now
      represented by the table. If it improved the table (old parent p
      had pos[p] > pos[v]), the displaced constraint "x ~ p from
      pos[p]" reduces to "v ~ p from pos[p]" (x~v merged strictly
      earlier), so the retiring slot is REUSED in place for (v, p).
      every other active edge (x, v) climbs: rewrite to (m, v) where m
      is x's highest ancestor with pos[m] < pos[v]          (gather)
    fixpoint: all slots dead -> the table is the elimination forest of
    every constraint inserted so far.

This is the vectorized form of the C++ core's incremental insertion
(core/csrc/sheep_core.cpp insert_edge: climb / displace-and-reinsert);
the represented constraint closure is preserved by every rewrite, so the
fixpoint is the unique elimination forest of the inserted multiset,
independent of edge order — which is what makes the build streamable and
the per-shard forests mergeable. Termination: a slot's pos[lo] strictly
increases on every climb AND on displacement spawn (the displaced
constraint's lo is the new parent, later than x), so each slot changes
at most n times; binary lifting makes it near-logarithmic in practice.

Unlike a formulation that re-materializes the carried forest's V tree
edges as active constraints each chunk, the active set here is O(C):
per-chunk transient memory and per-round work are independent of V
(BASELINE.md "HBM budget": single-chip ceiling 2^29 vertices at 16 GiB).

Every operation is a flat gather / scatter-min over static shapes; the
loop is a ``lax.while_loop``. Within each round the climb uses **binary
lifting** (pointer doubling): the parent map is squared ``lift_levels``
times (t_{j+1} = t_j[t_j], each a 2^j-step ancestor table) and every
edge jumps up the tables to its highest ancestor still earlier than
``hi``. Parent chains strictly increase in elimination position, so the
pos-bound predicate is monotone along a chain (measured: 645 -> 22
rounds on RMAT-14).

Two descent schedules, auto-selected by memory footprint:

- **exact** (high-to-low over precomputed tables): one round climbs each
  edge to its true highest admissible ancestor, fewest rounds, but all
  ``lift_levels`` tables are live at once -> O(V log V) working memory.
  Used while that fits ``EXACT_TABLE_BYTES`` (1 GiB default).
- **stream** (low-to-high, squaring interleaved with jumping): only one
  table is live -> O(V + C) memory, ~1.4x the rounds (greedy LSB-first
  jumping is not exact, but every taken jump is a sound rewrite, so the
  fixpoint is unchanged). Used for huge V where the table stack would
  blow HBM.

Sentinel encoding: index ``n`` means "none"; ``pos[n] = n`` acts as +inf,
``order[n] = n``. Inactive/padding edges are (n, n).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NO_PARENT = -1


@partial(jax.jit, static_argnames=("n",))
def orient_edges(edges: jax.Array, pos: jax.Array, n: int):
    """(C,2) int32 edges -> (lo, hi) with pos[lo] < pos[hi]; self-loops and
    out-of-range/padding endpoints become inactive (n, n)."""
    e = edges.astype(jnp.int32)
    u = jnp.clip(e[:, 0], 0, n)
    v = jnp.clip(e[:, 1], 0, n)
    pu, pv = pos[u], pos[v]
    lo = jnp.where(pu <= pv, u, v)
    hi = jnp.where(pu <= pv, v, u)
    bad = (lo == hi) | (pos[lo] == pos[hi])  # self-loop or both-sentinel
    lo = jnp.where(bad, n, lo)
    hi = jnp.where(bad, n, hi)
    return lo, hi


# exact descent keeps lift_levels ancestor tables of 4*(n+1) bytes live at
# once; beyond this budget the fixpoint switches to the O(V) stream descent
EXACT_TABLE_BYTES = 1 << 30


def _resolve(n: int, lift_levels: int, descent: str):
    if lift_levels <= 0:
        lift_levels = max(1, int(n).bit_length())
    if descent == "auto":
        table_bytes = lift_levels * 4 * (n + 1)
        descent = "exact" if table_bytes <= EXACT_TABLE_BYTES else "stream"
    return lift_levels, descent


def _round_body(pos, order, n: int, lift_levels: int, descent: str):
    """One fixpoint round as a while_loop body over state
    (lo, hi, minp, changed, rounds) — shared by the run-to-fixpoint and
    bounded-segment entry points so both execute identical rounds."""

    def body(state):
        lo_, hi_, minp_, _, rounds = state
        poshi = pos[hi_]
        old_at_lo = minp_[lo_]  # parent position BEFORE this round
        new_minp = minp_.at[lo_].min(poshi, mode="drop")
        now = new_minp[lo_]

        # climb for non-retiring edges. binary lifting: t_j[x] = x's
        # 2^j-step ancestor under the updated table (sentinel n is a
        # fixpoint of every table since minp[n] = n and order[n] = n);
        # a jump is safe iff the landing vertex is still earlier than hi
        t = order[new_minp]
        new_lo = lo_
        if descent == "exact":
            tables = [t]
            for _ in range(lift_levels - 1):
                t = t[t]
                tables.append(t)
            for t in reversed(tables):
                cand = t[new_lo]
                new_lo = jnp.where(pos[cand] < poshi, cand, new_lo)
        else:  # stream: square in place, only one table live
            for j in range(lift_levels):
                cand = t[new_lo]
                new_lo = jnp.where(pos[cand] < poshi, cand, new_lo)
                if j < lift_levels - 1:
                    t = t[t]
        became_loop = new_lo == hi_  # constraint already implied
        climb_lo = jnp.where(became_loop, n, new_lo)
        climb_hi = jnp.where(became_loop, n, hi_)

        # retire: this edge's target IS the min at lo (pos is injective,
        # so only duplicates of the same edge can retire together). If it
        # improved on an existing parent p, reuse the slot for the
        # displaced constraint (v, p); else the slot dies.
        retire = poshi == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, order[now], n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, order[old_at_lo], n),
                           climb_hi).astype(jnp.int32)
        # slots only ever change toward progress (pos[lo] strictly
        # increases), so "no slot changed" == fixpoint (table included:
        # the table only changes through a retiring slot)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, new_minp, changed, rounds + 1

    return body


def _init_state(minp, lo, hi):
    # derive the initial carry scalars from `lo` so their sharding/varying
    # axes match the loop body's outputs (required under shard_map)
    changed0 = lo[0] == lo[0]  # True, with lo's varying axes
    rounds0 = (lo[0] * 0).astype(jnp.int32)
    return (lo.astype(jnp.int32), hi.astype(jnp.int32),
            minp.astype(jnp.int32), changed0, rounds0)


@partial(jax.jit, static_argnames=("n", "lift_levels", "max_rounds", "descent"))
def fold_edges(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    max_rounds: int = 1 << 20,
    descent: str = "auto",
):
    """Fold active constraints (lo, hi) into the carried forest table.

    Returns (minp int32[n+1], rounds int32); minp[x] = elimination
    position of x's parent (n = root/no parent). The active buffer is
    fixed-size: a retiring slot is reused in place by the constraint it
    displaces, so per-round work is O(len(lo)), independent of V.

    ``lift_levels`` = number of doubled ancestor tables per round
    (0 -> auto: ceil(log2(n+1)), enough to cover any chain in one round).
    ``descent`` = "exact" | "stream" | "auto" (see module docstring).
    """
    lift_levels, descent = _resolve(n, lift_levels, descent)
    body = _round_body(pos, order, n, lift_levels, descent)

    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < max_rounds)

    state = _init_state(minp, lo, hi)
    _, _, minp_f, _, rounds = lax.while_loop(cond, body, state)
    return minp_f, rounds


@partial(jax.jit, static_argnames=("n", "lift_levels", "segment_rounds",
                                   "descent"))
def fold_edges_segment(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
    descent: str = "auto",
):
    """At most ``segment_rounds`` fixpoint rounds in ONE device execution.

    Returns the full loop state (lo, hi, minp, changed, rounds) so a host
    driver can resume where the segment stopped. Bounding the rounds per
    execution keeps each accelerator call short — long-running single
    executions are what tripped the TPU worker watchdog in round 2's
    first bench attempt — and gives the host a natural point to report
    progress. Rounds are executed by the same body as :func:`fold_edges`,
    so the segmented fixpoint is bit-identical to the monolithic one.
    """
    lift_levels, descent = _resolve(n, lift_levels, descent)
    body = _round_body(pos, order, n, lift_levels, descent)

    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < segment_rounds)

    state = _init_state(minp, lo, hi)
    return lax.while_loop(cond, body, state)


def _small_round_body(pos, order, n: int, jumps: int):
    """Jump-mode round body for SMALL active buffers: identical
    retire/displace semantics to :func:`_round_body`, but the climb is
    ``jumps`` single parent steps via per-element gathers — O(C') work per
    round with NO O(V) lifting-table rebuild. Used for the fixpoint tail,
    where a handful of displacement-chain constraints would otherwise pay
    the full-buffer, full-table cost every round."""

    def body(state):
        lo_, hi_, minp_, _, rounds = state
        poshi = pos[hi_]
        old_at_lo = minp_[lo_]
        new_minp = minp_.at[lo_].min(poshi, mode="drop")
        now = new_minp[lo_]

        cur = lo_
        for _ in range(jumps):
            cand_pos = new_minp[cur]
            cand = order[cand_pos]
            cur = jnp.where(cand_pos < poshi, cand, cur)
        became_loop = cur == hi_
        climb_lo = jnp.where(became_loop, n, cur)
        climb_hi = jnp.where(became_loop, n, hi_)

        retire = poshi == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, order[now], n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, order[old_at_lo], n),
                           climb_hi).astype(jnp.int32)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, new_minp, changed, rounds + 1

    return body


@partial(jax.jit, static_argnames=("n", "jumps", "segment_rounds"))
def fold_edges_segment_small(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    jumps: int = 8,
    segment_rounds: int = 64,
):
    """Bounded segment of jump-mode rounds (see _small_round_body)."""
    body = _small_round_body(pos, order, n, jumps)

    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < segment_rounds)

    return lax.while_loop(cond, body, _init_state(minp, lo, hi))


@partial(jax.jit, static_argnames=("n", "size"))
def compact_actives(lo: jax.Array, hi: jax.Array, n: int, size: int):
    """Pack the live constraints into a (size,) buffer, padding with the
    inert sentinel (n, n). Valid only when the live count <= size (the
    caller checks); slot identity is meaningless — only the multiset of
    active constraints matters to the fixpoint, so compaction is exact."""
    c = lo.shape[0]
    # fill slots index an appended sentinel row, so padding is inert
    sel = jnp.nonzero(lo != n, size=size, fill_value=c)[0]
    lo_ext = jnp.concatenate([lo, jnp.full(1, n, lo.dtype)])
    hi_ext = jnp.concatenate([hi, jnp.full(1, n, hi.dtype)])
    return lo_ext[sel], hi_ext[sel]


def count_live(lo: jax.Array, n: int) -> int:
    return int(jnp.sum(lo != n))


def _host_tail_finish(minp, lo, hi, pos, order, n: int, size: int,
                      pos_host=None):
    """Finish the fixpoint on HOST via the native core's Liu pass.

    The fixpoint tail is a displacement cascade — inherently sequential
    pointer-chasing that a vector machine resolves one link per round
    (measured: 6.8k tail rounds at RMAT-20 streamed in 4 chunks). The
    native C++ insertion resolves the whole cascade in O(total chain
    length) on host, so once the live count is small we pull the O(V)
    table + the compacted live constraints, extend the forest there, and
    push the table back. Same unique forest (cross-backend bit-identity
    is an existing test invariant)."""
    import numpy as np

    from sheep_tpu.core import native

    clo, chi = compact_actives(lo, hi, n, size)
    lo_np = np.asarray(clo)
    hi_np = np.asarray(chi)
    mask = lo_np != n
    edges = np.stack([lo_np[mask], hi_np[mask]], axis=1)
    if pos_host is None:
        pos_host = np.asarray(pos[:n])
    parent = minp_to_parent(minp, order, n)
    parent = native.build_elim_tree(edges, pos_host, parent)
    return parent_to_minp(parent, pos_host, n)


def fold_edges_adaptive(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    descent: str = "auto",
    max_rounds: int = 1 << 20,
    small_size: int = 1 << 14,
    small_jumps: int = 16,
    host_tail: bool = True,
    host_tail_threshold: int = 0,
    pos_host=None,
    stats=None,
):
    """Host-driven fixpoint with active-set compaction and a host-finished
    tail — same unique forest as :func:`fold_edges`, far less work.

    Measured motivation (RMAT-18, cpu-jax): 106 of 122 rounds had < 4k
    live constraints out of a 4.2M buffer, so >85% of build time was
    climbing dead slots and rebuilding lifting tables for them; at
    RMAT-20 the tail cascade alone was 6.8k rounds. Schedule:

    - full mode: lifting-table segments on the current buffer
    - after each segment, if live count <= size/4, compact the buffer to
      max(small_size, 2*live) rounded up to a power of two (each size is
      one extra compiled program; sizes shrink geometrically, so at most
      ~log16(C) programs exist)
    - once live <= ``host_tail_threshold`` and the native core is
      available, finish on host (:func:`_host_tail_finish`): the
      displacement cascade is sequential work the CPU does in O(chain),
      for one O(V) table round-trip per chunk
    - fallback (no native core): jump-mode rounds at ``small_size`` —
      O(C') gathers per round, independent of V
    """
    from sheep_tpu.core import native

    use_host_tail = host_tail and native.available()
    if stats is None:
        stats = {}
    total = 0
    size = int(lo.shape[0])
    if host_tail_threshold <= 0:
        # auto: hand off once <= size/8 constraints remain (min 2^16) —
        # the cpu-jax sweet spot; on a real chip device rounds are far
        # cheaper relative to the host pass, so callers may lower it
        host_tail_threshold = max(1 << 16, size // 8)
    while True:
        if size > small_size:
            seg = min(segment_rounds, max_rounds - total)
            lo, hi, minp, changed, r = fold_edges_segment(
                minp, lo, hi, pos, order, n, lift_levels=lift_levels,
                segment_rounds=seg, descent=descent)
            stats["full_segments"] = stats.get("full_segments", 0) + 1
        else:
            seg = min(max(segment_rounds, 64), max_rounds - total)
            lo, hi, minp, changed, r = fold_edges_segment_small(
                minp, lo, hi, pos, order, n, jumps=small_jumps,
                segment_rounds=seg)
            stats["small_segments"] = stats.get("small_segments", 0) + 1
        total += int(r)
        stats["device_rounds"] = stats.get("device_rounds", 0) + int(r)
        if not bool(changed) or total >= max_rounds:
            return minp, total
        live = count_live(lo, n)
        if use_host_tail and live <= host_tail_threshold:
            # fixed compact size -> one compiled compaction per input size
            stats["host_tails"] = stats.get("host_tails", 0) + 1
            stats["host_tail_live"] = stats.get("host_tail_live", 0) + live
            return (_host_tail_finish(minp, lo, hi, pos, order, n,
                                      min(host_tail_threshold, size),
                                      pos_host=pos_host),
                    total)
        if size > small_size and live <= size // 4:
            new_size = max(small_size, 1 << max(1, (2 * live - 1)
                                                .bit_length()))
            if new_size < size:
                lo, hi = compact_actives(lo, hi, n, new_size)
                size = new_size
                stats["compactions"] = stats.get("compactions", 0) + 1


def fold_edges_segmented(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
    descent: str = "auto",
    max_rounds: int = 1 << 20,
    on_segment=None,
):
    """Host-driven fixpoint: loop :func:`fold_edges_segment` until no slot
    changes. Same result as :func:`fold_edges`; one short device execution
    per ``segment_rounds`` rounds. ``on_segment(total_rounds)`` is called
    after each segment (progress/diagnostics hook)."""
    total = 0
    while True:
        # never run past max_rounds: the tail segment shrinks to the
        # remaining budget so the result matches fold_edges(max_rounds=...)
        # exactly (one extra compile at most, for the tail size)
        seg = min(segment_rounds, max_rounds - total)
        lo, hi, minp, changed, r = fold_edges_segment(
            minp, lo, hi, pos, order, n, lift_levels=lift_levels,
            segment_rounds=seg, descent=descent)
        total += int(r)
        if on_segment is not None:
            on_segment(total)
        if not bool(changed) or total >= max_rounds:
            return minp, total


def elim_fixpoint(
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    max_rounds: int = 1 << 20,
    descent: str = "auto",
):
    """Elimination forest of an oriented constraint set, from scratch —
    :func:`fold_edges` seeded with the empty table."""
    return fold_edges(jnp.full(n + 1, n, dtype=jnp.int32), lo, hi, pos,
                      order, n, lift_levels=lift_levels,
                      max_rounds=max_rounds, descent=descent)


def tree_edges_from_parent(parent_pos: jax.Array, order: jax.Array, n: int):
    """parent_pos (minp) int32[n+1] -> (lo, hi) arrays of the forest edges,
    inactive slots as (n, n). lo = vertex, hi = its parent."""
    v = jnp.arange(n + 1, dtype=jnp.int32)
    has = parent_pos < n
    lo = jnp.where(has, v, n)
    hi = jnp.where(has, order[parent_pos], n)
    return lo, hi


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def build_chunk_step(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
):
    """One streaming step: fold a (C, 2) edge chunk into the carried forest.

    parent_pos is the minp encoding (int32[n+1], n = no parent). The
    carried forest stays in the table — only the chunk's C edges are
    active (plus in-place displacement reuse), so per-chunk transients
    are O(C) and per-round work is independent of V. Device memory is
    O(V) tables + O(C) actives plus a bounded lifting-table stack (at
    most ``EXACT_TABLE_BYTES``; past that the stream descent keeps it
    one table) — the edge stream never materializes.
    """
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges(parent_pos, clo, chi, pos, order, n,
                      lift_levels=lift_levels)


def build_chunk_step_segmented(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
):
    """:func:`build_chunk_step` with host-bounded device executions
    (:func:`fold_edges_segmented`) — the single-device streaming path uses
    this so no one accelerator call runs unboundedly long."""
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges_segmented(parent_pos, clo, chi, pos, order, n,
                                lift_levels=lift_levels,
                                segment_rounds=segment_rounds)


def build_chunk_step_adaptive(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    pos_host=None,
    stats=None,
):
    """:func:`build_chunk_step` via :func:`fold_edges_adaptive`
    (compaction + host-finished tail) — the single-device streaming
    path's production fold: same unique forest, bounded device
    executions, and the sequential displacement cascade runs on host
    instead of one link per device round."""
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges_adaptive(parent_pos, clo, chi, pos, order, n,
                               lift_levels=lift_levels,
                               segment_rounds=segment_rounds,
                               pos_host=pos_host, stats=stats)


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def merge_forests(
    a_pos: jax.Array, b_pos: jax.Array, pos: jax.Array, order: jax.Array,
    n: int, lift_levels: int = 0,
):
    """Associative merge of two forests in minp encoding (SURVEY.md §2 #6):
    fold B's tree edges into A's table — T(A ∪ B) = T(T(A) ∪ T(B)).

    This is the cross-shard/device reduction combiner; the butterfly in
    ``parallel/pipeline.py`` ships each forest as either the O(V) table
    or compacted boundary pairs."""
    blo, bhi = tree_edges_from_parent(b_pos, order, n)
    minp, _ = fold_edges(a_pos, blo, bhi, pos, order, n,
                         lift_levels=lift_levels)
    return minp


def minp_to_parent(minp, order, n):
    """minp encoding -> parent array (int64[n], -1 for roots) on host."""
    import numpy as np

    minp = np.asarray(minp[:n])
    order = np.asarray(order)
    parent = np.where(minp < n, order[np.minimum(minp, n)], NO_PARENT)
    return parent.astype(np.int64)


def parent_to_minp(parent, pos, n):
    """parent array (int[n], -1 roots) -> device minp encoding int32[n+1]."""
    import numpy as np

    parent = np.asarray(parent)
    pos = np.asarray(pos)
    minp = np.full(n + 1, n, dtype=np.int32)
    has = parent >= 0
    minp[:n][has] = pos[parent[has]]
    return jnp.asarray(minp)
