"""Elimination-tree build as a data-parallel fixpoint (SURVEY.md §2 #4-6).

This is the TPU answer to the reference's sequential union-find hot loop
(SURVEY.md §7 hard part #1). Instead of pointer-chasing per edge, the build
is a *constraint-rewriting fixpoint* over the whole edge set:

    invariant  pos[lo] < pos[hi] for every active edge (lo, hi)
    round:
      minp[x]  = min over active edges at lo=x of pos[hi]   (scatter-min)
      m[x]     = order[minp[x]]   (x's current best parent candidate)
      rewrite  every non-min edge (x, v) -> (m[x], v)       (gather)
    at fixpoint every active edge is its lo's min edge, and
    parent[x] = m[x] is exactly the elimination tree.

Soundness of the rewrite: the min edge (x, m[x]) always stays in the set,
and given u~m[x] from time pos[m[x]] < pos[v], the constraint "u~v from
time pos[v]" is equivalent to "m[x]~v from time pos[v]". The fixpoint is
therefore the unique elimination forest of the inserted edge multiset,
regardless of edge order — the same argument that makes the C++ core's
incremental insertion (core/csrc/sheep_core.cpp) correct, vectorized.

Every operation is a flat gather / scatter-min over static shapes: no
data-dependent shapes, no host round-trips; the loop is a
``lax.while_loop``. Within each round the climb uses **binary lifting**
(pointer doubling): the candidate-parent map is squared ``lift_levels``
times (t_{j+1} = t_j[t_j], each a 2^j-step ancestor table) and every
edge jumps up the tables to its highest ancestor still earlier than
``hi``. Parent chains are strictly increasing in elimination position,
so the pos-bound predicate is monotone along a chain. This collapses the
round count from O(tree depth) to near-logarithmic (measured: 645 -> 22
rounds on RMAT-14), which is what makes deep scale-free elimination
trees viable on the MXU-less gather path.

Two descent schedules, auto-selected by memory footprint:

- **exact** (high-to-low over precomputed tables): one round climbs each
  edge to its true highest admissible ancestor, fewest rounds, but all
  ``lift_levels`` tables are live at once -> O(V log V) working memory.
  Used while that fits ``EXACT_TABLE_BYTES`` (1 GiB default).
- **stream** (low-to-high, squaring interleaved with jumping): only one
  table is live -> O(V + C) memory, ~1.4x the rounds (greedy LSB-first
  jumping is not exact, but every taken jump is a sound rewrite, so the
  fixpoint is unchanged). Used for huge V where the table stack would
  blow HBM.

Sentinel encoding: index ``n`` means "none"; ``pos[n] = n`` acts as +inf,
``order[n] = n``. Inactive/padding edges are (n, n).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NO_PARENT = -1


@partial(jax.jit, static_argnames=("n",))
def orient_edges(edges: jax.Array, pos: jax.Array, n: int):
    """(C,2) int32 edges -> (lo, hi) with pos[lo] < pos[hi]; self-loops and
    out-of-range/padding endpoints become inactive (n, n)."""
    e = edges.astype(jnp.int32)
    u = jnp.clip(e[:, 0], 0, n)
    v = jnp.clip(e[:, 1], 0, n)
    pu, pv = pos[u], pos[v]
    lo = jnp.where(pu <= pv, u, v)
    hi = jnp.where(pu <= pv, v, u)
    bad = (lo == hi) | (pos[lo] == pos[hi])  # self-loop or both-sentinel
    lo = jnp.where(bad, n, lo)
    hi = jnp.where(bad, n, hi)
    return lo, hi


# exact descent keeps lift_levels ancestor tables of 4*(n+1) bytes live at
# once; beyond this budget the fixpoint switches to the O(V) stream descent
EXACT_TABLE_BYTES = 1 << 30


@partial(jax.jit, static_argnames=("n", "lift_levels", "max_rounds", "descent"))
def elim_fixpoint(
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    max_rounds: int = 1 << 20,
    descent: str = "auto",
):
    """Run the rewrite fixpoint; returns (minp int32[n+1], rounds int32).

    minp[x] = elimination position of x's parent (n = root/no parent).
    ``lift_levels`` = number of doubled ancestor tables per round
    (0 -> auto: ceil(log2(n+1)), enough to cover any chain in one round).
    ``descent`` = "exact" | "stream" | "auto" (see module docstring).
    """
    if lift_levels <= 0:
        lift_levels = max(1, int(n).bit_length())
    if descent == "auto":
        table_bytes = lift_levels * 4 * (n + 1)
        descent = "exact" if table_bytes <= EXACT_TABLE_BYTES else "stream"
    inf = jnp.int32(n)

    def scatter_min(lo_, poshi_):
        return jnp.full(n + 1, inf, dtype=jnp.int32).at[lo_].min(poshi_, mode="drop")

    def body(state):
        lo_, hi_, _, rounds = state
        poshi = pos[hi_]
        minp = scatter_min(lo_, poshi)
        # binary lifting: t_j[x] = x's 2^j-step ancestor under the current
        # candidate-parent map (sentinel n is a fixpoint of every table
        # since minp[n] = n and order[n] = n). A jump is safe iff its
        # landing vertex is still earlier than hi (chains strictly
        # increase in pos).
        t = order[minp]
        new_lo = lo_
        if descent == "exact":
            tables = [t]
            for _ in range(lift_levels - 1):
                t = t[t]
                tables.append(t)
            for t in reversed(tables):
                cand = t[new_lo]
                new_lo = jnp.where(pos[cand] < poshi, cand, new_lo)
        else:  # stream: square in place, only one table live
            for j in range(lift_levels):
                cand = t[new_lo]
                new_lo = jnp.where(pos[cand] < poshi, cand, new_lo)
                if j < lift_levels - 1:
                    t = t[t]
        # edge became its lo's min edge or a self-loop -> deactivate
        became_loop = new_lo == hi_
        new_lo = jnp.where(became_loop, n, new_lo)
        new_hi = jnp.where(became_loop, n, hi_)
        changed = jnp.any(new_lo != lo_)
        return new_lo, new_hi, changed, rounds + 1

    def cond(state):
        _, _, changed, rounds = state
        return changed & (rounds < max_rounds)

    # derive the initial carry scalars from `lo` so their sharding/varying
    # axes match the loop body's outputs (required under shard_map)
    changed0 = lo[0] == lo[0]  # True, with lo's varying axes
    rounds0 = (lo[0] * 0).astype(jnp.int32)
    state = (lo, hi, changed0, rounds0)
    lo_f, hi_f, _, rounds = lax.while_loop(cond, body, state)
    minp = scatter_min(lo_f, pos[hi_f])
    return minp, rounds


def tree_edges_from_parent(parent_pos: jax.Array, order: jax.Array, n: int):
    """parent_pos (minp) int32[n+1] -> (lo, hi) arrays of the forest edges,
    inactive slots as (n, n). lo = vertex, hi = its parent."""
    v = jnp.arange(n + 1, dtype=jnp.int32)
    has = parent_pos < n
    lo = jnp.where(has, v, n)
    hi = jnp.where(has, order[parent_pos], n)
    return lo, hi


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def build_chunk_step(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
):
    """One streaming step: fold a (C, 2) edge chunk into the carried forest.

    parent_pos is the minp encoding (int32[n+1], n = no parent). By the
    merge identity T(G1 ∪ G2) = T(T(G1) ∪ T(G2)), folding the chunk into
    the existing forest's edges yields the forest of all edges seen so far.
    Device memory is O(V + C) plus a bounded lifting-table stack (at most
    ``EXACT_TABLE_BYTES``; past that the stream descent keeps it O(V)) —
    the edge stream never materializes.
    """
    tlo, thi = tree_edges_from_parent(parent_pos, order, n)
    clo, chi = orient_edges(chunk, pos, n)
    lo = jnp.concatenate([tlo, clo])
    hi = jnp.concatenate([thi, chi])
    minp, rounds = elim_fixpoint(lo, hi, pos, order, n, lift_levels=lift_levels)
    return minp, rounds


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def merge_forests(
    a_pos: jax.Array, b_pos: jax.Array, pos: jax.Array, order: jax.Array,
    n: int, lift_levels: int = 0,
):
    """Associative merge of two forests in minp encoding (SURVEY.md §2 #6).

    This is the cross-shard/device reduction: each forest is O(V), so a
    log2(D) ppermute reduction moves O(V log D) bytes over ICI."""
    alo, ahi = tree_edges_from_parent(a_pos, order, n)
    blo, bhi = tree_edges_from_parent(b_pos, order, n)
    lo = jnp.concatenate([alo, blo])
    hi = jnp.concatenate([ahi, bhi])
    minp, _ = elim_fixpoint(lo, hi, pos, order, n, lift_levels=lift_levels)
    return minp


def minp_to_parent(minp, order, n):
    """minp encoding -> parent array (int64[n], -1 for roots) on host."""
    import numpy as np

    minp = np.asarray(minp[:n])
    order = np.asarray(order)
    parent = np.where(minp < n, order[np.minimum(minp, n)], NO_PARENT)
    return parent.astype(np.int64)


def parent_to_minp(parent, pos, n):
    """parent array (int[n], -1 roots) -> device minp encoding int32[n+1]."""
    import numpy as np

    parent = np.asarray(parent)
    pos = np.asarray(pos)
    minp = np.full(n + 1, n, dtype=np.int32)
    has = parent >= 0
    minp[:n][has] = pos[parent[has]]
    return jnp.asarray(minp)
